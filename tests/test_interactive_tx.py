"""Interactive multi-statement transactions through SQL: deferred
effects, atomic cross-table commit, optimistic conflict abort,
repeatable reads, rollback (reference: session tx state in
kqp_session_actor.cpp + datashard locks; SURVEY §2.8)."""

import pytest

from ydb_tpu.kqp.session import Cluster, PlanError
from ydb_tpu.tx.coordinator import TxResult


@pytest.fixture
def cluster():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE acct (id int64, bal int64, "
              "PRIMARY KEY (id)) WITH (store = row, shards = 2)")
    s.execute("CREATE TABLE log (seq int64, note int64, "
              "PRIMARY KEY (seq)) WITH (store = row)")
    s.execute("INSERT INTO acct VALUES (1, 100), (2, 50)")
    return c


def val(s, sql, col):
    out = s.execute(sql)
    return [int(x) for x in out.column(col)]


def test_commit_applies_atomically_across_tables(cluster):
    s = cluster.session()
    assert s.execute("BEGIN") is None
    s.execute("UPDATE acct SET bal = bal - 30 WHERE id = 1")
    s.execute("UPDATE acct SET bal = bal + 30 WHERE id = 2")
    s.execute("INSERT INTO log VALUES (1, 30)")
    # deferred effects: another session sees nothing yet
    other = cluster.session()
    assert val(other, "SELECT bal FROM acct ORDER BY id", "bal") == \
        [100, 50]
    assert val(other, "SELECT seq FROM log", "seq") == []
    res = s.execute("COMMIT")
    assert isinstance(res, TxResult) and res.committed
    # all three effects land at ONE step
    assert val(other, "SELECT bal FROM acct ORDER BY id", "bal") == \
        [70, 80]
    assert val(other, "SELECT note FROM log", "note") == [30]


def test_rollback_discards_and_releases(cluster):
    s = cluster.session()
    s.execute("BEGIN")
    s.execute("UPDATE acct SET bal = 0 WHERE id = 1")
    assert s.execute("ROLLBACK") is None
    assert val(s, "SELECT bal FROM acct WHERE id = 1", "bal") == [100]
    # locks released: another session's write proceeds and commits
    other = cluster.session()
    other.execute("UPDATE acct SET bal = 7 WHERE id = 2")
    assert val(s, "SELECT bal FROM acct WHERE id = 2", "bal") == [7]


def test_conflicting_commit_aborts_transaction(cluster):
    a = cluster.session()
    a.execute("BEGIN")
    a.execute("UPDATE acct SET bal = bal - 10 WHERE id = 1")

    b = cluster.session()  # concurrent writer commits first
    b.execute("UPDATE acct SET bal = 999 WHERE id = 1")

    res = a.execute("COMMIT")
    assert isinstance(res, TxResult) and not res.committed
    # b's write survives; a's buffered effect never landed
    assert val(b, "SELECT bal FROM acct WHERE id = 1", "bal") == [999]


def test_repeatable_reads_at_begin_snapshot(cluster):
    a = cluster.session()
    a.execute("BEGIN")
    assert val(a, "SELECT bal FROM acct WHERE id = 1", "bal") == [100]
    b = cluster.session()
    b.execute("UPDATE acct SET bal = 5 WHERE id = 1")
    # a still reads the BEGIN snapshot
    assert val(a, "SELECT bal FROM acct WHERE id = 1", "bal") == [100]
    a.execute("ROLLBACK")
    assert val(a, "SELECT bal FROM acct WHERE id = 1", "bal") == [5]


def test_insert_then_read_own_write_not_visible_until_commit(cluster):
    """Deferred-effect model: the transaction does NOT see its own
    buffered writes (documented semantics)."""
    s = cluster.session()
    s.execute("BEGIN")
    s.execute("INSERT INTO log VALUES (9, 1)")
    assert val(s, "SELECT seq FROM log", "seq") == []
    s.execute("COMMIT")
    assert val(s, "SELECT seq FROM log", "seq") == [9]


def test_tx_statement_errors(cluster):
    s = cluster.session()
    with pytest.raises(PlanError):
        s.execute("COMMIT")  # no open tx
    s.execute("BEGIN")
    with pytest.raises(PlanError):
        s.execute("BEGIN")  # nested
    s.execute("ROLLBACK")
    s.execute("BEGIN")
    with pytest.raises(PlanError, match="DDL"):
        s.execute("CREATE TABLE t2 (id int64, PRIMARY KEY (id))")
    # the failed DDL aborted the tx; a new BEGIN works
    s.execute("BEGIN")
    s.execute("ROLLBACK")


def test_no_lost_update_between_begin_and_first_touch(cluster):
    """A commit landing between BEGIN and the tx's first touch of a
    table must abort the tx, not be clobbered by stale full-row
    writes (code-review regression, confirmed repro)."""
    s = cluster.session()
    s.execute("ALTER TABLE acct ADD COLUMN x int64")
    s.execute("UPDATE acct SET x = 0 WHERE id = 1")
    a = cluster.session()
    a.execute("BEGIN")
    b = cluster.session()
    b.execute("UPDATE acct SET x = 777 WHERE id = 1")  # after BEGIN
    with pytest.raises(PlanError, match="changed after BEGIN"):
        a.execute("UPDATE acct SET bal = bal - 10 WHERE id = 1")
    # b's committed write intact, a's tx gone
    out = b.execute("SELECT x, bal FROM acct WHERE id = 1")
    assert int(out.column("x")[0]) == 777
    assert int(out.column("bal")[0]) == 100
    assert a._tx is None


def test_scalar_subquery_reads_tx_snapshot(cluster):
    """Subqueries inside a tx must see the BEGIN snapshot, matching
    the outer statement (code-review regression, confirmed repro)."""
    a = cluster.session()
    a.execute("BEGIN")
    b = cluster.session()
    b.execute("UPDATE acct SET bal = 999 WHERE id = 2")
    out = a.execute(
        "SELECT id FROM acct WHERE bal = (SELECT max(bal) FROM acct)")
    # at the BEGIN snapshot max(bal)=100 on id 1, not b's 999
    assert [int(x) for x in out.column("id")] == [1]
    a.execute("ROLLBACK")


def test_empty_commit_is_trivially_true(cluster):
    s = cluster.session()
    s.execute("BEGIN")
    res = s.execute("COMMIT")
    assert res.committed
