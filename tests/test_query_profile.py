"""End-to-end query profiling: span-threaded execution, EXPLAIN
ANALYZE actuals vs probe values, trace-id propagation across DQ /
conveyor threads, sys_top_queries / sys_query_log, latency histograms
on /counters/prometheus, profile ring bounding, disabled path."""

import json
import threading

import numpy as np
import pytest

from ydb_tpu.kqp.session import Cluster
from ydb_tpu.obs import tracing
from ydb_tpu.obs.counters import Histogram
from ydb_tpu.obs.probes import TraceSession
from ydb_tpu.obs.profile import ProfileRing, build_profile
from ydb_tpu.obs.tracing import Tracer


MAIN_THREAD = threading.get_ident()


@pytest.fixture
def cluster():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE ev (id int64, ts int64, v int64, "
              "PRIMARY KEY (id)) WITH (shards = 2)")
    # several commits -> several portions per shard
    for base in (0, 100, 200):
        vals = ", ".join(f"({base + i}, {base + i}, {(base + i) * 3})"
                         for i in range(8))
        s.execute(f"INSERT INTO ev VALUES {vals}")
    return c


def lineitem_cluster(sf=0.002):
    """A Cluster holding TPC-H lineitem (several portions per shard)."""
    from ydb_tpu.scheme.model import type_to_str
    from ydb_tpu.workload import tpch

    data = tpch.TpchData(sf=sf, seed=7)
    c = Cluster()
    s = c.session()
    cols = ", ".join(
        f"{f.name} {type_to_str(f.type)}"
        for f in tpch.LINEITEM_SCHEMA.fields)
    s.execute(f"CREATE TABLE lineitem ({cols}, "
              "PRIMARY KEY (l_orderkey)) WITH (shards = 1)")
    li = data.tables["lineitem"]
    t = c.tables["lineitem"]
    n = len(li["l_orderkey"])
    step = max(1, n // 3)
    for off in range(0, n, step):  # 3 commits -> 3 portions
        arrays = {}
        for f in tpch.LINEITEM_SCHEMA.fields:
            v = li[f.name][off:off + step]
            if f.type.is_string:
                arrays[f.name] = [
                    bytes(x) for x in data.dicts[f.name].decode(
                        np.asarray(v, dtype=np.int32))]
            else:
                arrays[f.name] = v
        t.insert(arrays)
    c._invalidate_plans()
    return c, li


# ---------- span-threaded execution ----------

def test_span_tree_shape_single_stage(cluster):
    s = cluster.session()
    out = s.execute("SELECT ts, sum(v) AS sv FROM ev "
                    "GROUP BY ts ORDER BY ts LIMIT 5")
    assert out.num_rows == 5
    p = s.last_profile
    assert p is not None
    names = {sp["name"] for sp in p.spans}
    assert {"query", "plan", "parse", "execute", "scan",
            "fetch"} <= names
    by_id = {sp["span_id"]: sp for sp in p.spans}
    # every span belongs to one trace and parents resolve inside it
    root = next(sp for sp in p.spans if sp["parent_id"] is None)
    assert root["name"] == "query"
    for sp in p.spans:
        if sp["parent_id"] is not None:
            assert sp["parent_id"] in by_id
    # parse nests under plan nests under query
    parse = next(sp for sp in p.spans if sp["name"] == "parse")
    assert by_id[parse["parent_id"]]["name"] == "plan"
    assert by_id[by_id[parse["parent_id"]]["parent_id"]]["name"] == \
        "query"


def test_span_tree_shape_multi_stage_dq(cluster):
    s = cluster.session()
    s.execute("CREATE TABLE dim (ts int64, label int64, "
              "PRIMARY KEY (ts))")
    vals = ", ".join(f"({i}, {i % 4})" for i in range(0, 300))
    s.execute(f"INSERT INTO dim VALUES {vals}")
    out = s.execute(
        "SELECT d.label, count(*) AS n FROM ev e "
        "JOIN dim d ON e.ts = d.ts GROUP BY d.label ORDER BY d.label")
    assert out.num_rows > 0
    p = s.last_profile
    names = {sp["name"] for sp in p.spans}
    assert "dq" in names, names
    tasks = [sp for sp in p.spans if sp["name"] == "dq.task"]
    assert len(tasks) >= 3  # scan stages + join + final
    stages = {sp["attrs"]["stage"] for sp in tasks}
    assert len(stages) >= 3
    assert all("compute_seconds" in sp["attrs"] for sp in tasks)
    dq = next(sp for sp in p.spans if sp["name"] == "dq")
    assert dq["attrs"]["stages"] >= 4
    assert p.query_class == "select_join"
    # device time for a join query comes from the tasks' accumulated
    # compute seconds (there are no scan/transform spans on this path)
    task_compute = sum(sp["attrs"]["compute_seconds"] for sp in tasks)
    assert task_compute > 0
    assert p.stages["compute"] == pytest.approx(task_compute, abs=1e-6)
    assert p.device_seconds == p.stages["compute"]


def test_trace_id_propagates_to_conveyor_producer(cluster):
    s = cluster.session()
    s.execute("SELECT sum(v) AS sv FROM ev")
    p = s.last_profile
    producers = [sp for sp in p.spans if sp["name"] == "scan.producer"]
    assert producers, "no prefetch producer span recorded"
    # the producer ran on a conveyor worker, not the session thread,
    # yet its span landed in the SAME trace
    assert any(sp["attrs"]["thread"] != MAIN_THREAD
               for sp in producers)
    assert all(
        sp["span_id"] in {q["span_id"] for q in p.spans}
        for sp in producers)


def test_compile_vs_execute_split_across_runs(cluster):
    sql = "SELECT ts, sum(v) AS sv FROM ev GROUP BY ts"
    s = cluster.session()
    s.execute(sql)
    first = s.last_profile
    assert first.plan_cache == "miss"
    assert first.compile_cache == "miss"
    assert first.compile_seconds > 0          # lowering + first trace
    assert first.execute_seconds >= 0
    names = {sp["name"] for sp in first.spans}
    assert "ssa.compile" in names
    s.execute(sql)
    second = s.last_profile
    assert second.plan_cache == "hit"
    assert second.compile_cache == "hit"       # warm: no retrace
    assert second.compile_seconds == 0.0
    assert second.seconds < first.seconds
    # compile-cache counters aggregate per cluster
    snap = cluster.counters.snapshot()
    assert snap.get("miss|component=kqp,kind=compile_cache", 0) >= 1
    assert snap.get("hit|component=kqp,kind=compile_cache", 0) >= 1


def test_scan_stage_seconds_and_pruning_attrs(cluster):
    s = cluster.session()
    s.execute("SELECT sum(v) AS sv FROM ev WHERE ts >= 200")
    p = s.last_profile
    assert p.pruning["portions_total"] > 0
    assert p.pruning["portions_skipped"] > 0   # zone maps pruned
    assert p.pruning["chunks_read"] > 0
    assert set(p.stages) == {"read", "merge", "stage", "compute"}
    assert p.stages["read"] > 0
    assert p.stages["compute"] > 0
    assert p.device_seconds == p.stages["compute"]
    assert p.host_seconds >= p.stages["read"]


# ---------- EXPLAIN ANALYZE ----------

def test_explain_analyze_actuals_match_probes(cluster):
    sql = ("EXPLAIN ANALYZE SELECT ts, sum(v) AS sv FROM ev "
           "WHERE ts >= 100 GROUP BY ts")
    s = cluster.session()
    with TraceSession("columnshard.scan.*") as ts:
        txt = s.execute(sql)
    assert "TableScan ev" in txt and "-- actuals --" in txt
    assert "compile_cache=miss" in txt
    prune = [p for n, p in ts.events
             if n == "columnshard.scan.pruning" and p["shard"] == -1]
    stages = [p for n, p in ts.events
              if n == "columnshard.scan.stages" and p["shard"] == -1]
    assert prune and stages
    pr, st = prune[-1], stages[-1]
    for k in ("portions_total", "portions_skipped", "chunks_read",
              "chunks_skipped"):
        assert f"{k}={pr[k]}" in txt
    for k in ("read", "merge", "stage", "compute"):
        assert f"{k}={st[k]:.6f}" in txt
    # second consecutive run: warm execute, no compile
    txt2 = s.execute(sql)
    assert "compile_cache=hit" in txt2
    assert "compile_seconds=0.000000" in txt2


def test_explain_analyze_tpch_q1():
    from ydb_tpu.workload.queries import TPCH

    c, li = lineitem_cluster()
    s = c.session()
    with TraceSession("columnshard.scan.*") as ts:
        txt = s.execute("EXPLAIN ANALYZE " + TPCH["q1"])
    assert "TableScan lineitem" in txt
    assert "compile_cache=miss" in txt
    pr = [p for n, p in ts.events
          if n == "columnshard.scan.pruning" and p["shard"] == -1][-1]
    assert f"chunks_read={pr['chunks_read']}" in txt
    assert pr["chunks_read"] > 0
    st = [p for n, p in ts.events
          if n == "columnshard.scan.stages" and p["shard"] == -1][-1]
    for k in ("read", "stage", "compute"):
        assert f"{k}={st[k]:.6f}" in txt
    # the measured total covers its parts
    total = float(txt.split("seconds=")[1].split()[0])
    assert total > 0
    txt2 = s.execute("EXPLAIN ANALYZE " + TPCH["q1"])
    assert "compile_cache=hit" in txt2
    assert "compile_seconds=0.000000" in txt2
    # the analyzed query really ran: row counts match a direct SELECT
    out = s.execute(TPCH["q1"])
    assert f"rows={out.num_rows}" in txt2


def test_plain_explain_unchanged(cluster):
    s = cluster.session()
    txt = s.execute("EXPLAIN SELECT sum(v) AS sv FROM ev")
    assert "TableScan ev" in txt
    assert "-- actuals --" not in txt


# ---------- sys views + viewer + counters ----------

def test_top_queries_and_query_log_sysviews(cluster):
    s = cluster.session()
    s.execute("SELECT ts, sum(v) AS sv FROM ev GROUP BY ts")
    out = s.execute(
        "SELECT rank, query_text, query_class, seconds, rows, "
        "compile_seconds, compile_cache FROM sys_top_queries "
        "ORDER BY rank")
    assert out.num_rows >= 3
    ranks = list(out.column("rank"))
    assert ranks == sorted(ranks)
    texts = [v.decode() for v in out.strings("query_text")]
    assert any("GROUP BY ts" in t for t in texts)
    classes = [v.decode() for v in out.strings("query_class")]
    assert "select_agg" in classes
    # seconds ordered most-expensive-first
    secs = list(out.column("seconds"))
    assert secs == sorted(secs, reverse=True)

    log = s.execute("SELECT seq, kind, spans FROM sys_query_log "
                    "ORDER BY seq")
    seqs = list(log.column("seq"))
    assert seqs == sorted(seqs) and len(seqs) >= 4
    assert all(n > 0 for n in log.column("spans"))


def test_viewer_query_profile_endpoint(cluster):
    from ydb_tpu.obs.viewer import Viewer

    s = cluster.session()
    s.execute("SELECT ts, sum(v) AS sv FROM ev GROUP BY ts")
    v = Viewer(cluster).start()
    try:
        import urllib.request

        with urllib.request.urlopen(
                f"http://127.0.0.1:{v.port}/viewer/json/query_profile",
                timeout=10) as r:
            assert r.status == 200
            payload = json.loads(r.read())
        assert payload["top"] and payload["last"]
        last = payload["last"]
        assert last["span_tree"], "span tree missing"
        assert last["stages"]["compute"] >= 0
        seq = payload["recent"][-1]["seq"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{v.port}"
                f"/viewer/json/query_profile?seq={seq}",
                timeout=10) as r:
            one = json.loads(r.read())
        assert one["seq"] == seq
        # the HTML page carries the profiles tab
        with urllib.request.urlopen(
                f"http://127.0.0.1:{v.port}/viewer", timeout=10) as r:
            assert b"profiles" in r.read()
    finally:
        v.stop()


def test_prometheus_latency_histograms(cluster):
    s = cluster.session()
    s.execute("SELECT ts, sum(v) AS sv FROM ev GROUP BY ts")
    s.execute("SELECT v FROM ev LIMIT 3")
    text = cluster.counters.encode_prometheus()
    assert 'query_latency_seconds_bucket' in text
    assert 'query_class="select_agg"' in text
    assert 'query_class="select_scan"' in text
    # p50/p99 gauges ride beside the raw histogram
    p50 = [ln for ln in text.splitlines()
           if ln.startswith("query_latency_p50")
           and 'query_class="select_agg"' in ln]
    assert p50 and float(p50[0].rsplit(" ", 1)[1]) > 0
    assert any(ln.startswith("query_latency_p99")
               for ln in text.splitlines())


# ---------- ring bounding + disabled path ----------

def test_profile_ring_bounded(cluster):
    cluster.profiles = ProfileRing(capacity=4)
    s = cluster.session()
    for i in range(9):
        s.execute(f"SELECT v FROM ev WHERE id = {i}")
    assert len(cluster.profiles) == 4
    recent = cluster.profiles.recent()
    # ring keeps the LAST 4, seq keeps counting
    assert [p.seq for p in recent] == sorted(p.seq for p in recent)
    assert recent[-1].seq == 9
    assert len(cluster.profiles.top(16)) == 4


def test_disabled_path():
    tracing.PROFILE_FORCE = False
    try:
        c = Cluster()
        s = c.session()
        s.execute("CREATE TABLE ev (id int64, v int64, "
                  "PRIMARY KEY (id))")
        s.execute("INSERT INTO ev VALUES (1, 2), (2, 4)")
        out = s.execute("SELECT sum(v) AS sv FROM ev")
        assert out.num_rows == 1
        assert s.last_profile is None
        assert len(c.profiles) == 0
        # root/plan/execute spans remain (the pre-profile surface),
        # nothing deeper
        q = [sp for sp in c.tracer.finished
             if sp.name == "query"][-1]
        names = {sp.name
                 for sp in c.tracer.spans_for(q.trace_id)}
        assert names == {"query", "plan", "execute"}
        # no per-class histogram was touched
        text = c.counters.encode_prometheus()
        assert "query_latency_seconds" not in text
        # EXPLAIN ANALYZE still runs and reports totals
        txt = s.execute("EXPLAIN ANALYZE SELECT sum(v) AS sv FROM ev")
        assert "-- actuals --" in txt and "total: seconds=" in txt
    finally:
        tracing.PROFILE_FORCE = None


# ---------- tracer thread-safety + index ----------

def test_tracer_concurrent_finish_and_index():
    tr = Tracer(max_spans=500)
    roots = [tr.trace(f"q{i}") for i in range(8)]
    errs = []

    def hammer(root):
        try:
            for _ in range(100):
                root.child("w").set(thread=threading.get_ident()) \
                    .finish()
        except Exception as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=hammer, args=(r,))
               for r in roots]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len(tr.finished) == 500  # bounded (8 * 100 > 500)
    # the index agrees with the ring after eviction
    total = sum(len(tr.spans_for(r.trace_id)) for r in roots)
    assert total == 500
    for r in roots:
        for sp in tr.spans_for(r.trace_id):
            assert sp.trace_id == r.trace_id


def test_tracer_index_lookup_matches_linear_scan():
    tr = Tracer()
    with tr.trace("a") as a:
        a.child("x").finish()
    with tr.trace("b") as b:
        b.child("y").finish()
        b.child("z").finish()
    assert {s.name for s in tr.spans_for(a.trace_id)} == {"a", "x"}
    assert {s.name for s in tr.spans_for(b.trace_id)} == {"b", "y", "z"}
    assert tr.spans_for(999999) == []


# ---------- histogram satellite ----------

def test_histogram_interpolates_within_bucket():
    h = Histogram(bounds=(1.0, 2.0, 4.0))
    h.observe(1.5)
    assert h.percentile(0.5) == pytest.approx(1.5)
    h2 = Histogram(bounds=(1.0, 2.0))
    for _ in range(4):
        h2.observe(1.1)  # all land in (1, 2]
    # quartiles spread linearly across the winning bucket
    assert 1.0 < h2.percentile(0.25) < h2.percentile(0.75) < 2.0


def test_histogram_submillisecond_p50_not_quantized():
    h = Histogram()  # default bounds now reach 1us
    for _ in range(50):
        h.observe(0.0004)  # 400us device op
    p50 = h.percentile(0.5)
    assert p50 < 0.001, "sub-ms p50 quantized to the old 1ms floor"
    assert p50 > 1e-5


def test_histogram_overflow_and_empty():
    h = Histogram(bounds=(1.0, 2.0))
    assert h.percentile(0.5) == 0.0
    h.observe(50.0)
    assert h.percentile(0.5) == 2.0  # finite (top bound), not inf


# ---------- profile assembly unit ----------

def test_build_profile_aggregates_scan_spans():
    tr = Tracer()
    root = tr.trace("query")
    sc1 = root.child("scan").set(
        table="a", rows=10, compile_cache="miss",
        first_trace_seconds=0.5, stage_read=0.1, stage_compute=0.2,
        portions_total=4, portions_skipped=1, chunks_read=3,
        chunks_skipped=2)
    sc1.finish()
    sc2 = root.child("shard.scan").set(
        shard=0, rows=5, compile_cache="hit", stage_read=0.3,
        stage_compute=0.1, portions_total=2, portions_skipped=0,
        chunks_read=1, chunks_skipped=0)
    sc2.finish()
    root.finish()
    p = build_profile(tr.spans_for(root.trace_id), sql="q",
                      kind="select", seconds=2.0)
    assert p.rows == 15
    assert p.compile_cache == "miss"
    assert p.compile_seconds == pytest.approx(0.5)
    assert p.execute_seconds == pytest.approx(1.5)
    assert p.stages["read"] == pytest.approx(0.4)
    assert p.stages["compute"] == pytest.approx(0.3)
    assert p.pruning == {"portions_total": 6, "portions_skipped": 1,
                         "chunks_read": 4, "chunks_skipped": 2,
                         "resident_portions": 0, "resident_rows": 0}
    assert p.device_seconds == pytest.approx(0.3)
    tree = p.span_tree()
    assert tree[0]["name"] == "query"
    assert {c["name"] for c in tree[0]["children"]} == \
        {"scan", "shard.scan"}
