"""SSA program compiler/kernels tests.

Coverage mirrors the reference's SSA program unit tests
(ydb/core/tx/columnshard/engines/ut/ut_program.cpp) and block-agg node
tests (minikql/comp_nodes/ut/) — rebuilt for the JAX lowering.
"""

import jax
import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.blocks import DictionarySet, TableBlock
from ydb_tpu.ssa import (
    Agg,
    AggSpec,
    AssignStep,
    Call,
    Col,
    DictPredicate,
    FilterStep,
    GroupByStep,
    Op,
    ProjectStep,
    Program,
    SortStep,
    compile_program,
)
from ydb_tpu.ssa.program import decimal_lit, lit


def _block(**cols):
    """Build a block from name -> (np array, logical type[, validity])."""
    sch = []
    arrays = {}
    validity = {}
    for name, spec in cols.items():
        arr, t = spec[0], spec[1]
        sch.append((name, t))
        arrays[name] = np.asarray(arr)
        if len(spec) > 2:
            validity[name] = np.asarray(spec[2])
    return TableBlock.from_numpy(arrays, dtypes.schema(*sch), validity or None)


def test_filter_and_arith():
    blk = _block(
        a=([1, 2, 3, 4, 5], dtypes.INT64),
        b=([10, 20, 30, 40, 50], dtypes.INT64),
    )
    prog = Program((
        AssignStep("c", Call(Op.ADD, Col("a"), Col("b"))),
        FilterStep(Call(Op.GT, Col("c"), lit(33))),
        ProjectStep(("a", "c")),
    ))
    cp = compile_program(prog, blk.schema)
    out = jax.jit(cp.run)(blk, {k: np.asarray(v) for k, v in cp.aux.items()})
    res = out.to_numpy()
    np.testing.assert_array_equal(res["a"], [4, 5])
    np.testing.assert_array_equal(res["c"], [44, 55])


def test_null_propagation_and_kleene():
    blk = _block(
        a=([1, 2, 3], dtypes.INT64, [True, False, True]),
        b=([5, 5, 0], dtypes.INT64),
    )
    prog = Program((
        AssignStep("gt", Call(Op.GT, Col("a"), lit(0))),
        AssignStep("div", Call(Op.DIV, Col("b"), Col("a"))),
        # null > 0 -> null; filter treats null as false
        FilterStep(Col("gt")),
    ))
    cp = compile_program(prog, blk.schema)
    out = cp(blk)
    res = out.to_numpy()
    np.testing.assert_array_equal(res["a"], [1, 3])
    v = out.validity_numpy()
    # 5/1 fine; 0/3 fine
    np.testing.assert_array_equal(v["div"], [True, True])


def test_div_by_zero_is_null():
    blk = _block(
        a=([10, 10], dtypes.INT64),
        b=([2, 0], dtypes.INT64),
    )
    prog = Program((AssignStep("q", Call(Op.DIV, Col("a"), Col("b"))),))
    cp = compile_program(prog, blk.schema)
    out = cp(blk)
    np.testing.assert_array_equal(out.validity_numpy()["q"], [True, False])
    assert out.to_numpy()["q"][0] == 5


def test_decimal_arith_and_rescale():
    blk = _block(
        price=([100_00, 250_50], dtypes.decimal(2)),
        disc=([5, 10], dtypes.decimal(2)),  # 0.05, 0.10
    )
    prog = Program((
        # price * (1 - disc): classic TPC-H Q1 expression
        AssignStep("one_minus", Call(Op.SUB, decimal_lit("1", 2), Col("disc"))),
        AssignStep("dp", Call(Op.MUL, Col("price"), Col("one_minus"))),
    ))
    cp = compile_program(prog, blk.schema)
    out = cp(blk)
    assert out.schema.field("dp").type.scale == 4
    np.testing.assert_array_equal(
        out.to_numpy()["dp"], [100_00 * 95, 250_50 * 90]
    )


def test_dict_predicates():
    dicts = DictionarySet()
    ids = dicts.for_column("s").encode([b"AIR", b"MAIL", b"SHIP", b"AIR"])
    blk = _block(s=(ids, dtypes.STRING))
    prog = Program((
        FilterStep(DictPredicate("s", "eq", b"AIR")),
    ))
    cp = compile_program(prog, blk.schema, dicts)
    out = cp(blk)
    assert int(out.length) == 2

    prog2 = Program((
        FilterStep(DictPredicate("s", "in_set", (b"MAIL", b"SHIP"))),
    ))
    out2 = compile_program(prog2, blk.schema, dicts)(blk)
    assert int(out2.length) == 2


def test_group_by_dense_with_strings():
    dicts = DictionarySet()
    flag = dicts.for_column("flag").encode([b"A", b"B", b"A", b"A", b"B"])
    blk = _block(
        flag=(flag, dtypes.STRING),
        qty=([1.0, 2.0, 3.0, 4.0, 100.0], dtypes.DOUBLE),
    )
    prog = Program((
        GroupByStep(
            keys=("flag",),
            aggs=(
                AggSpec(Agg.SUM, "qty", "sum_qty"),
                AggSpec(Agg.AVG, "qty", "avg_qty"),
                AggSpec(Agg.COUNT_ALL, None, "n"),
            ),
        ),
    ))
    cp = compile_program(prog, blk.schema, dicts)
    out = cp(blk)
    res = out.to_numpy()
    assert int(out.length) == 2
    by_flag = {
        dicts["flag"].values[int(f)]: (s, a, n)
        for f, s, a, n in zip(res["flag"], res["sum_qty"], res["avg_qty"], res["n"])
    }
    assert by_flag[b"A"] == (8.0, 8.0 / 3, 3)
    assert by_flag[b"B"] == (102.0, 51.0, 2)


def test_group_by_sorted_path_generic_keys():
    blk = _block(
        k=([7, 3, 7, 3, 9, 7], dtypes.INT64),
        v=([1, 2, 3, 4, 5, 6], dtypes.INT64),
    )
    prog = Program((
        GroupByStep(
            keys=("k",),
            aggs=(
                AggSpec(Agg.SUM, "v", "sv"),
                AggSpec(Agg.MIN, "v", "mn"),
                AggSpec(Agg.MAX, "v", "mx"),
            ),
            max_groups=16,
        ),
    ))
    cp = compile_program(prog, blk.schema)
    out = cp(blk)
    res = out.to_numpy()
    assert int(out.length) == 3
    # sorted group-id path yields key-ordered groups
    np.testing.assert_array_equal(res["k"], [3, 7, 9])
    np.testing.assert_array_equal(res["sv"], [6, 10, 5])
    np.testing.assert_array_equal(res["mn"], [2, 1, 5])
    np.testing.assert_array_equal(res["mx"], [4, 6, 5])


def test_group_by_null_key_and_null_values():
    blk = _block(
        k=([1, 1, 2, 2], dtypes.INT64, [True, False, True, True]),
        v=([10, 20, 30, 40], dtypes.INT64, [True, True, False, True]),
    )
    prog = Program((
        GroupByStep(
            keys=("k",),
            aggs=(
                AggSpec(Agg.SUM, "v", "sv"),
                AggSpec(Agg.COUNT, "v", "cnt"),
                AggSpec(Agg.COUNT_ALL, None, "n"),
            ),
            max_groups=8,
        ),
    ))
    cp = compile_program(prog, blk.schema)
    out = cp(blk)
    res = out.to_numpy()
    valid = out.validity_numpy()
    assert int(out.length) == 3  # NULL, 1, 2
    rows = {}
    for i in range(3):
        key = None if not valid["k"][i] else int(res["k"][i])
        rows[key] = (int(res["sv"][i]), int(res["cnt"][i]), int(res["n"][i]))
    assert rows[None] == (20, 1, 1)
    assert rows[1] == (10, 1, 1)
    assert rows[2] == (40, 1, 2)  # one null v: sum=40, cnt=1, n=2


def test_global_aggregate_no_keys():
    blk = _block(v=([1.5, 2.5, 4.0], dtypes.DOUBLE))
    prog = Program((
        GroupByStep(keys=(), aggs=(
            AggSpec(Agg.SUM, "v", "s"),
            AggSpec(Agg.COUNT_ALL, None, "n"),
        )),
    ))
    out = compile_program(prog, blk.schema)(blk)
    assert int(out.length) == 1
    assert out.to_numpy()["s"][0] == 8.0
    assert out.to_numpy()["n"][0] == 3


def test_sort_desc_with_limit():
    blk = _block(
        a=([5, 1, 4, 2, 3], dtypes.INT64),
        b=([50, 10, 40, 20, 30], dtypes.INT64),
    )
    prog = Program((
        SortStep(keys=("a",), descending=(True,), limit=3),
    ))
    out = compile_program(prog, blk.schema)(blk)
    res = out.to_numpy()
    np.testing.assert_array_equal(res["a"], [5, 4, 3])
    np.testing.assert_array_equal(res["b"], [50, 40, 30])


def test_year_extract():
    # 2020-01-01 is day 18262
    blk = _block(d=([0, 18262, 19723], dtypes.DATE))
    prog = Program((
        AssignStep("y", Call(Op.YEAR, Col("d"))),
        AssignStep("m", Call(Op.MONTH, Col("d"))),
    ))
    out = compile_program(prog, blk.schema)(blk)
    res = out.to_numpy()
    np.testing.assert_array_equal(res["y"], [1970, 2020, 2024])
    np.testing.assert_array_equal(res["m"], [1, 1, 1])


def test_jit_cache_stability():
    """Same program + same block shape => no retrace (pattern-cache analog)."""
    sch = dtypes.schema(("a", dtypes.INT64))
    prog = Program((FilterStep(Call(Op.GT, Col("a"), lit(1))),))
    cp = compile_program(prog, sch)
    traced = jax.jit(cp.run)
    b1 = TableBlock.from_numpy({"a": np.arange(10, dtype=np.int64)}, sch)
    b2 = TableBlock.from_numpy({"a": np.arange(500, dtype=np.int64)}, sch)
    aux = {k: np.asarray(v) for k, v in cp.aux.items()}
    traced(b1, aux)
    traced(b2, aux)  # same padded capacity -> cache hit
    assert traced._cache_size() == 1


def test_var_stddev_aggregates_match_numpy():
    """VAR_SAMP/STDDEV_SAMP: grouped + keyless, nulls ignored, groups
    with fewer than two non-null values yield NULL; compiled JAX plane
    cross-checked against the CPU oracle and raw numpy."""
    import numpy as np

    from ydb_tpu import dtypes
    from ydb_tpu.engine.oracle import OracleTable, run_oracle
    from ydb_tpu.engine.scan import ColumnSource, execute_scan
    from ydb_tpu.ssa.ops import Agg
    from ydb_tpu.ssa.program import AggSpec, GroupByStep, Program

    rng = np.random.default_rng(11)
    n = 5000
    g = rng.integers(0, 7, n).astype(np.int64)
    v = rng.integers(-1000, 1000, n).astype(np.int64)
    valid = rng.random(n) > 0.1
    # group 5: exactly one non-null value -> NULL var; group 6: empty
    valid[g == 5] = False
    one = np.flatnonzero(g == 5)[0]
    valid[one] = True
    valid[g == 6] = False
    sch = dtypes.schema(("g", dtypes.INT64, False),
                        ("v", dtypes.INT64))
    prog = Program((GroupByStep(
        keys=("g",),
        aggs=(AggSpec(Agg.VAR_SAMP, "v", "var"),
              AggSpec(Agg.STDDEV_SAMP, "v", "sd"),
              AggSpec(Agg.COUNT, "v", "n"))),))
    src = ColumnSource({"g": g, "v": v}, sch,
                       validity={"v": valid})
    out = execute_scan(prog, src, block_rows=1 << 10)  # multi-block:
    # exercises the two-phase partial/finalize split
    table = OracleTable({"g": (g, np.ones(n, bool)),
                         "v": (v, valid)}, sch)
    ora = run_oracle(prog, table)
    got_g = np.asarray(out.cols["g"][0])
    order = np.argsort(got_g)
    for name in ("var", "sd", "n"):
        gv, gok = (np.asarray(out.cols[name][0])[order],
                   np.asarray(out.cols[name][1])[order])
        ov, ook = (np.asarray(ora.cols[name][0]),
                   np.asarray(ora.cols[name][1]))
        oorder = np.argsort(np.asarray(ora.cols["g"][0]))
        assert np.array_equal(gok, ook[oorder]), name
        assert np.allclose(gv[gok], ov[oorder][gok], rtol=1e-9), name
    # independent numpy check per group
    for gi in range(7):
        m = (g == gi) & valid
        i = np.flatnonzero(np.asarray(out.cols["g"][0]) == gi)
        if m.sum() >= 2:
            assert np.isclose(
                float(np.asarray(out.cols["var"][0])[i[0]]),
                float(np.var(v[m], ddof=1)), rtol=1e-9), gi
            assert np.isclose(
                float(np.asarray(out.cols["sd"][0])[i[0]]),
                float(np.std(v[m], ddof=1)), rtol=1e-9), gi
        elif len(i):
            assert not bool(np.asarray(out.cols["var"][1])[i[0]]), gi


def test_window_rank_functions_match_oracle():
    """rank/dense_rank/row_number over (partition, order) — device
    lexsort+segment-scan plane vs the oracle's independent python-sort
    implementation, with a filter ahead of the window (masked rows are
    excluded) and ties in the order keys."""
    import numpy as np

    from ydb_tpu import dtypes
    from ydb_tpu.engine.oracle import OracleTable, run_oracle
    from ydb_tpu.blocks.block import TableBlock
    from ydb_tpu.ssa.compiler import compile_program
    from ydb_tpu.ssa.program import (
        Call, Col, FilterStep, Program, WindowStep, lit,
    )
    from ydb_tpu.ssa.ops import Op
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(3)
    n = 4000
    g = rng.integers(0, 11, n).astype(np.int64)
    v = rng.integers(0, 25, n).astype(np.int64)  # many ties
    k = rng.permutation(n).astype(np.int64)
    sch = dtypes.schema(("g", dtypes.INT64, False),
                        ("v", dtypes.INT64, False),
                        ("k", dtypes.INT64, False))
    prog = Program((
        FilterStep(Call(Op.GT, Col("v"), lit(2))),
        WindowStep("rank", ("g",), ("v",), (True,), "rnk"),
        WindowStep("dense_rank", ("g",), ("v",), (True,), "dr"),
        WindowStep("row_number", ("g",), ("v", "k"), (True, False),
                   "rn"),
    ))
    cp = compile_program(prog, sch, None, None)
    blk = TableBlock.from_numpy({"g": g, "v": v, "k": k}, sch)
    out = jax.jit(cp.run)(
        blk, {kk: jnp.asarray(vv) for kk, vv in cp.aux.items()})
    table = OracleTable(
        {"g": (g, np.ones(n, bool)), "v": (v, np.ones(n, bool)),
         "k": (k, np.ones(n, bool))}, sch)
    ora = run_oracle(prog, table)
    got = out.to_numpy()
    # align by the unique row key k
    go = np.argsort(got["k"])
    oo = np.argsort(np.asarray(ora.cols["k"][0]))
    for name in ("rnk", "dr", "rn"):
        assert np.array_equal(
            got[name][go], np.asarray(ora.cols[name][0])[oo]), name
    # independent spot check: within each group, the max v has rank 1
    gg, vv_, rr = got["g"], got["v"], got["rnk"]
    for gi in np.unique(gg):
        m = gg == gi
        assert rr[m][np.argmax(vv_[m])] == 1
