"""Observability + config tests: counters, tracing, sys views via SQL,
health check, YAML config, ICB knobs, feature flags (SURVEY.md §5.1,
§5.5, §5.6)."""

import pytest

from ydb_tpu.config import AppConfig, ConfigError, ControlBoard
from ydb_tpu.kqp.session import Cluster
from ydb_tpu.obs.counters import CounterGroup
from ydb_tpu.obs.tracing import Tracer
from ydb_tpu.sql.planner import PlanError


# ---------- counters ----------

def test_counter_tree_and_prometheus_encoding():
    root = CounterGroup({"component": "test"})
    g = root.group(kind="select")
    g.counter("queries").inc()
    g.counter("queries").inc(2)
    g.histogram("latency_seconds").observe(0.003)
    text = root.encode_prometheus()
    assert 'queries{component="test",kind="select"} 3' in text
    assert "latency_seconds_count" in text
    assert g.histogram("latency_seconds").percentile(0.5) > 0


# ---------- tracing ----------

def test_span_nesting_and_export():
    tr = Tracer()
    with tr.trace("query") as root:
        with root.child("plan"):
            pass
        with root.child("execute") as ex:
            ex.set(rows=10)
    spans = tr.spans_for(root.trace_id)
    assert {s.name for s in spans} == {"query", "plan", "execute"}
    by_name = {s.name: s for s in spans}
    assert by_name["plan"].parent_id == by_name["query"].span_id
    assert "resourceSpans" in tr.export_otlp_json()


def test_session_emits_spans_and_counters():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id))")
    s.execute("INSERT INTO t VALUES (1)")
    s.execute("SELECT id FROM t")
    kinds = [sp.attrs.get("kind") for sp in c.tracer.finished
             if sp.name == "query"]
    assert "createtable" in kinds and "select" in kinds
    snap = c.counters.snapshot()
    assert any("queries" in k and "kind=select" in k and v == 1
               for k, v in snap.items())
    assert len(c.query_log) == 3


# ---------- sys views ----------

def test_sys_views_via_sql():
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id)) "
              "WITH (shards = 2)")
    s.execute("INSERT INTO t VALUES (1), (2), (3)")
    out = s.execute("SELECT table_name, rows FROM sys_partition_stats "
                    "WHERE table_name = 't'")
    assert sum(out.column("rows")) == 3
    out = s.execute("SELECT kind, count(*) AS n FROM sys_query_stats "
                    "GROUP BY kind ORDER BY kind")
    kinds = [v.decode() for v in out.strings("kind")]
    assert "insert" in kinds
    out = s.execute("SELECT path FROM sys_scheme_paths ORDER BY path")
    paths = [v.decode() for v in out.strings("path")]
    assert "/t" in paths


def test_sys_views_can_be_disabled():
    from ydb_tpu.config import FeatureFlags

    c = Cluster(config=AppConfig(
        feature_flags=FeatureFlags(enable_sys_views=False)))
    s = c.session()
    with pytest.raises(PlanError):
        s.execute("SELECT path FROM sys_scheme_paths")


# ---------- health ----------

def test_health_check_good_and_degraded():
    from ydb_tpu.blobstorage import DSProxy, GroupBlobStore, GroupInfo

    group = GroupInfo(1, "block42")
    c = Cluster(store=GroupBlobStore(DSProxy(group)))
    assert c.health()["status"] == "GOOD"
    group.disks[0].down = True
    h = c.health()
    assert h["status"] == "DEGRADED"
    assert any("disk" in i["message"] for i in h["issues"])
    group.disks[1].down = True
    group.disks[2].down = True
    assert c.health()["status"] == "EMERGENCY"


# ---------- config ----------

def test_yaml_config_parse_and_validation():
    cfg = AppConfig.from_yaml("""
n_shards: 8
plan_cache_size: 16
auth_tokens: [a, b]
feature_flags:
  enable_changefeeds: false
""")
    assert cfg.n_shards == 8
    assert cfg.auth_tokens == ("a", "b")
    assert cfg.feature_flags.enable_changefeeds is False
    with pytest.raises(ConfigError):
        AppConfig.from_yaml("nope: 1")
    with pytest.raises(ConfigError):
        AppConfig.from_yaml("n_shards: many")
    with pytest.raises(ConfigError):
        AppConfig.from_yaml("feature_flags:\n  bogus_flag: true")
    with pytest.raises(ConfigError):
        AppConfig.from_yaml("n_shards: 0")


def test_config_drives_cluster_defaults_and_flags():
    from ydb_tpu.config import FeatureFlags

    cfg = AppConfig(n_shards=2, feature_flags=FeatureFlags(
        enable_changefeeds=False))
    c = Cluster(config=cfg)
    s = c.session()
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id))")
    assert len(c.tables["t"].shards) == 2
    with pytest.raises(PlanError):
        s.execute("CREATE TABLE u (id int64, PRIMARY KEY (id)) "
                  "WITH (store = row, changefeed = on)")


def test_icb_knobs_clamp_and_apply():
    board = ControlBoard()
    board.register("k", default=5, lo=1, hi=10)
    assert board.set("k", 100) == 10      # clamped
    assert board.get("k") == 10
    board.reset("k")
    assert board.get("k") == 5

    # live compaction-threshold tuning takes effect in run_background
    c = Cluster()
    s = c.session()
    s.execute("CREATE TABLE t (id int64, PRIMARY KEY (id)) "
              "WITH (shards = 1)")
    for i in range(4):
        s.execute(f"INSERT INTO t VALUES ({i})")
    shard = c.tables["t"].shards[0]
    assert len(shard.visible_portions()) == 4
    c.icb.set("compact_portion_threshold", 2)
    c.run_background()
    assert len(shard.visible_portions()) == 1  # compacted under new knob


def test_histogram_export_has_inf_bucket():
    g = CounterGroup()
    h = g.histogram("lat", bounds=(1.0, 2.0))
    h.observe(5.0)  # beyond the top bound
    text = g.encode_prometheus()
    assert 'lat_bucket{le="+Inf"} 1' in text
    assert "lat_count 1" in text


def test_trace_id_propagation_no_collision():
    tr = Tracer()
    with tr.trace("remote", trace_id=7):
        pass
    with tr.trace("local") as local:
        pass
    assert local.trace_id != 7
    assert len(tr.spans_for(7)) == 1
