"""TPC-DS subset through SQL parse -> plan -> device execution, verified
against independent numpy reference implementations (the canondata
pattern; reference ydb/library/workload/tpcds/,
ydb/library/benchmarks/queries/tpcds/)."""

import numpy as np
import pytest

from ydb_tpu.engine.scan import ColumnSource
from ydb_tpu.plan import Database, execute_plan, to_host
from ydb_tpu.sql.parser import parse
from ydb_tpu.sql.planner import Catalog, plan_select_full
from ydb_tpu.workload import tpcds


@pytest.fixture(scope="module")
def data():
    return tpcds.TpcdsData(sf=0.002, seed=7)


@pytest.fixture(scope="module")
def db(data):
    return Database(
        sources={t: ColumnSource(cols, tpcds.SCHEMAS[t], data.dicts)
                 for t, cols in data.tables.items()},
        dicts=data.dicts,
    )


@pytest.fixture(scope="module")
def catalog(data):
    return Catalog(schemas=dict(tpcds.SCHEMAS),
                   primary_keys=dict(tpcds.PRIMARY_KEYS),
                   dicts=data.dicts)


@pytest.mark.parametrize("name", sorted(tpcds.QUERIES))
def test_query(name, data, db, catalog):
    from ydb_tpu.workload.runner import scalar_exec_for

    pq = plan_select_full(parse(tpcds.QUERIES[name]), catalog,
                          scalar_exec_for(db))
    out = to_host(execute_plan(pq.plan, db))
    want = tpcds.reference_answers(data, [name])[name]
    assert len(want) > 0, f"{name}: vacuous reference (generator issue)"
    if name in ("q38", "q96", "q16", "q94"):
        # count-shaped queries always yield one row; a zero count would
        # verify nothing about the join/exists machinery under test
        assert want[0][0] > 0, f"{name}: zero-count reference"
    tpcds.verify_result(name, out, want, data, pq)


def test_self_join_string_compare(data, db, catalog):
    """Two columns sharing one dictionary must not collapse to a single
    xrank hidden column (code-review regression: the hidden name must be
    keyed on the operand columns, not the dictionary sources)."""
    sql = ("select count(*) as c "
           "from store_sales, store s1, store s2 "
           "where ss_store_sk = s1.s_store_sk "
           "and ss_promo_sk = s2.s_store_sk "
           "and s1.s_zip <> s2.s_zip")
    pq = plan_select_full(parse(sql), catalog)
    out = to_host(execute_plan(pq.plan, db))
    st = data.tables["store"]
    zips = dict(zip(st["s_store_sk"].tolist(),
                    data.dicts["s_zip"].decode(st["s_zip"])))
    ss = data.tables["store_sales"]
    want = sum(
        1 for sk, pk in zip(ss["ss_store_sk"].tolist(),
                            ss["ss_promo_sk"].tolist())
        if pk in zips and zips[sk] != zips[pk])
    got = int(np.asarray(out.cols["c"][0])[0])
    assert got == want and want > 0, (got, want)


def test_generator_shapes(data):
    for t, cols in data.tables.items():
        sch = tpcds.SCHEMAS[t]
        assert set(cols) == set(sch.names)
        n = {len(v) for v in cols.values()}
        assert len(n) == 1, f"{t}: ragged columns"
        for name in sch.names:
            f = sch.field(name)
            if f.type.is_string:
                ids = cols[name]
                assert ids.dtype == np.int32
                assert int(ids.max()) < len(data.dicts[name])
