"""Executor pools: parallel pool threads, FIFO per mailbox, cross-pool
location transparency (SURVEY §2.2 executor-pools row)."""

import threading
import time

from ydb_tpu.runtime.actors import Actor
from ydb_tpu.runtime.pools import ThreadedPools


class Collector(Actor):
    def __init__(self):
        super().__init__()
        self.got = []
        self.threads = set()

    def receive(self, message, sender):
        self.threads.add(threading.get_ident())
        self.got.append(message)
        if isinstance(message, tuple) and message[0] == "ping":
            self.send(sender, ("pong", message[1]))


class Pinger(Actor):
    def __init__(self, peer, n):
        super().__init__()
        self.peer = peer
        self.n = n
        self.pongs = []

    def on_start(self):
        for i in range(self.n):
            self.send(self.peer, ("ping", i))

    def receive(self, message, sender):
        self.pongs.append(message[1])


def test_cross_pool_ping_pong_preserves_order():
    pools = ThreadedPools(n_pools=3)
    col = Collector()
    col_id = pools.register(col, pool=2)
    ping = Pinger(col_id, 50)
    pools.register(ping, pool=0)
    pools.start()
    try:
        deadline = time.monotonic() + 15
        while len(ping.pongs) < 50 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert ping.pongs == list(range(50))  # FIFO both directions
        assert [m[1] for m in col.got] == list(range(50))
    finally:
        pools.stop()


def test_pools_run_on_distinct_threads():
    pools = ThreadedPools(n_pools=2)
    a, b = Collector(), Collector()
    ida = pools.register(a, pool=0)
    idb = pools.register(b, pool=1)
    pools.start()
    try:
        for i in range(20):
            pools.send(ida, i)
            pools.send(idb, i)
        pools.drain()
        assert len(a.got) == len(b.got) == 20
        assert a.threads and b.threads and a.threads != b.threads
        stats = pools.stats()
        assert sum(s["delivered"] for s in stats) >= 40
    finally:
        pools.stop()
