"""ClickBench workload: every implemented query verified against the
independent numpy reference answers (the canondata pattern,
ydb/tests/functional/clickbench; VERDICT r4 item 10)."""

from ydb_tpu.workload.clickbench import QUERIES, run_clickbench


def test_clickbench_queries_match_reference():
    results = run_clickbench(rows=20_000, seed=3, verify=True)
    assert len(results) == len(QUERIES) == 43  # full official suite
    for name, seconds, rows in results:
        # q19 filters on a fixed spec UserID constant that synthetic
        # data never contains: a verified-empty result is correct
        assert rows >= 1 or name == "q19"


def test_clickbench_cli_verb(capsys):
    from ydb_tpu.cli import main

    main(["workload", "clickbench", "--rows", "5000", "--queries",
          "q0,q1,q7"])
    out = capsys.readouterr().out
    assert "q0" in out and "q7" in out
