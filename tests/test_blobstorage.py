"""BlobStorage tests: erasure codecs, quorum DSProxy, restore-on-read,
self-heal, and a full SQL cluster on erasure-coded storage with disk
kills (SURVEY.md §2.3)."""

import itertools

import numpy as np
import pytest

from ydb_tpu.blobstorage.erasure import ErasureCodec
from ydb_tpu.blobstorage.group import DSProxy, GroupInfo, VDisk
from ydb_tpu.blobstorage.proxy_store import GroupBlobStore
from ydb_tpu.kqp.session import Cluster


PAYLOADS = [b"", b"x", b"hello world", bytes(range(256)) * 37,
            np.random.default_rng(5).bytes(10000)]


@pytest.mark.parametrize("species", ["none", "mirror3", "block42"])
def test_erasure_roundtrip(species):
    codec = ErasureCodec(species)
    for data in PAYLOADS:
        parts = codec.encode(data)
        assert len(parts) == codec.total_parts
        full = {i: p for i, p in enumerate(parts)}
        assert codec.decode(full, len(data)) == data


def test_block42_recovers_any_two_lost_parts():
    codec = ErasureCodec("block42")
    data = np.random.default_rng(1).bytes(5000)
    parts = codec.encode(data)
    for lost in itertools.combinations(range(6), 2):
        have = {i: p for i, p in enumerate(parts) if i not in lost}
        assert codec.decode(have, len(data)) == data
    # and any single loss
    for lost1 in range(6):
        have = {i: p for i, p in enumerate(parts) if i != lost1}
        assert codec.decode(have, len(data)) == data
    # three losses must fail
    with pytest.raises(ValueError):
        codec.decode({i: parts[i] for i in (0, 4, 5)}, len(data))


def test_mirror3_recovers_two_lost():
    codec = ErasureCodec("mirror3")
    data = b"important"
    parts = codec.encode(data)
    assert codec.decode({2: parts[2]}, len(data)) == data


def test_reconstruct_part_matches_original():
    codec = ErasureCodec("block42")
    data = np.random.default_rng(2).bytes(3000)
    parts = codec.encode(data)
    for idx in range(6):
        have = {i: p for i, p in enumerate(parts) if i != idx}
        assert codec.reconstruct_part(have, idx, len(data)) == parts[idx]


def test_dsproxy_put_get_with_disks_down():
    group = GroupInfo(1, "block42")
    proxy = DSProxy(group)
    blobs = {f"blob/{i}": np.random.default_rng(i).bytes(100 + i * 37)
             for i in range(20)}
    for bid, data in blobs.items():
        proxy.put(bid, data)
    # restore-on-read with any two disks down
    group.disks[1].down = True
    group.disks[4].down = True
    for bid, data in blobs.items():
        assert proxy.get(bid) == data
    assert sorted(proxy.list("blob/")) == sorted(blobs)
    # a third down disk: reads start failing for some blobs
    group.disks[0].down = True
    failures = 0
    for bid, data in blobs.items():
        try:
            assert proxy.get(bid) == data
        except (ValueError, KeyError):
            failures += 1
    assert failures > 0


def test_dsproxy_write_quorum():
    group = GroupInfo(2, "block42")
    proxy = DSProxy(group)
    group.disks[0].down = True
    group.disks[1].down = True
    proxy.put("b1", b"still ok with 4/6")     # exactly at quorum
    assert proxy.get("b1") == b"still ok with 4/6"
    group.disks[2].down = True
    with pytest.raises(IOError):
        proxy.put("b2", b"3/6 is below quorum")


def test_self_heal_rebuilds_dead_disk():
    group = GroupInfo(3, "block42")
    proxy = DSProxy(group)
    blobs = {f"x/{i}": bytes([i]) * (50 + i) for i in range(30)}
    for bid, data in blobs.items():
        proxy.put(bid, data)
    group.disks[2].down = True
    rebuilt = proxy.self_heal(2)
    assert rebuilt > 0
    # now a DIFFERENT pair of disks can die and everything still reads
    group.disks[0].down = True
    group.disks[5].down = True
    for bid, data in blobs.items():
        assert proxy.get(bid) == data


def test_full_sql_cluster_on_erasure_coded_storage():
    group = GroupInfo(7, "block42")
    store = GroupBlobStore(DSProxy(group))
    c = Cluster(store=store)
    s = c.session()
    s.execute("CREATE TABLE t (id int64, name string, PRIMARY KEY (id)) "
              "WITH (shards = 2)")
    s.execute("INSERT INTO t VALUES (1, 'a'), (2, 'b'), (3, 'c')")
    # two disks die; the whole database still reads AND writes
    group.disks[0].down = True
    group.disks[3].down = True
    s.execute("INSERT INTO t VALUES (4, 'd')")
    out = s.execute("SELECT count(*) AS n FROM t")
    assert list(out.column("n")) == [4]
    # cluster reboot from the degraded group
    c2 = Cluster(store=store)
    out = c2.session().execute("SELECT id FROM t ORDER BY id")
    assert list(out.column("id")) == [1, 2, 3, 4]
    # heal, then a different failure pattern
    proxy = store.proxy
    proxy.self_heal(0)
    proxy.self_heal(3)
    group.disks[1].down = True
    group.disks[4].down = True
    out = c2.session().execute("SELECT id FROM t ORDER BY id")
    assert list(out.column("id")) == [1, 2, 3, 4]


def test_failed_overwrite_keeps_previous_version():
    """A failed overwrite during an outage must leave the old, still-
    valid version readable (versioned blob ids; no in-place part
    overwrite)."""
    group = GroupInfo(11, "block42")
    proxy = DSProxy(group)
    proxy.put("doc", b"version one")
    for i in range(3):
        group.disks[i].down = True
    with pytest.raises(IOError):
        proxy.put("doc", b"version two")
    for i in range(3):
        group.disks[i].down = False
    assert proxy.get("doc") == b"version one"
    # successful overwrite supersedes
    proxy.put("doc", b"version three")
    assert proxy.get("doc") == b"version three"


def test_mirror3_put_requires_all_replicas_placed():
    """mirror3 must place all 3 replicas (handoff onto survivors when a
    disk is down), never accept a 1-replica put as quorum."""
    group = GroupInfo(12, "mirror3")
    proxy = DSProxy(group)
    group.disks[0].down = True
    proxy.put("m", b"data")
    # all three replica parts exist despite the dead disk
    n_parts = 0
    for disk in group.disks:
        if disk.down:
            continue
        for part in range(3):
            n_parts += len(disk.list_parts(part, prefix="m@"))
    assert n_parts == 3
    assert proxy.get("m") == b"data"


def test_failed_put_rolls_back_and_self_heal_skips_garbage():
    group = GroupInfo(9, "block42")
    proxy = DSProxy(group)
    proxy.put("good", b"fine")
    group.disks[0].down = True
    group.disks[1].down = True
    group.disks[2].down = True
    with pytest.raises(IOError):
        proxy.put("partial", b"should roll back")
    group.disks[0].down = False
    group.disks[1].down = False
    group.disks[2].down = False
    assert not proxy.exists("partial")     # no poisoned remnant
    assert proxy.self_heal(4) >= 1         # heal still works
    assert proxy.get("good") == b"fine"


def test_rejoined_disk_resyncs_in_background():
    """synclog-lite anti-entropy (VERDICT r4 item 8; reference
    vdisk/syncer/): a disk that was DOWN during writes converges via
    resync() after rejoining — its designated parts restored, stale
    versions dropped — so a LATER double-disk outage (block42's full
    loss tolerance) still leaves every blob readable. Without resync
    the group would be carrying a silent third effective loss."""
    from ydb_tpu.blobstorage.group import DSProxy, GroupInfo

    g = GroupInfo(7)
    p = DSProxy(g)
    for i in range(6):
        p.put(f"pre{i}", b"old-%d" % i * 40)
    # disk 2 dies; writes continue (handoff placement covers it)
    g.disks[2].down = True
    for i in range(8):
        p.put(f"mid{i}", b"during-%d" % i * 40)
    p.put("pre0", b"overwritten" * 40)   # supersede during the outage
    p.delete("pre1")                     # delete during the outage
    # disk 2 rejoins with its OLD data; background resync runs
    g.disks[2].down = False
    moved = p.resync()
    assert moved > 0
    # the rejoined disk now holds its DESIGNATED parts of every blob
    # written while it was away (not just readable-via-reconstruct)
    n = len(g.disks)
    for i in range(8):
        bid = f"mid{i}"
        vid = p._vid(bid, p._seqs(bid)[0])
        from ydb_tpu.blobstorage.group import hash_rotation

        rot = hash_rotation(bid, n)
        for part in range(p.codec.total_parts):
            if g.disks[(part + rot) % n] is g.disks[2]:
                assert g.disks[2].has_part(vid, part), (bid, part)
    # stale state reconciled: superseded + deleted versions are gone
    assert not g.disks[2].list_parts(DSProxy.META_PART, prefix="pre1@")
    assert len(g.disks[2].list_parts(DSProxy.META_PART,
                                     prefix="pre0@")) <= 1
    # NOW kill two DIFFERENT disks — block42's full tolerance — and
    # everything must still read without any repair pass
    g.disks[4].down = True
    g.disks[5].down = True
    for i in range(6):
        if i == 1:
            continue  # deleted
        want = (b"overwritten" * 40 if i == 0 else b"old-%d" % i * 40)
        assert p.get(f"pre{i}") == want
    for i in range(8):
        assert p.get(f"mid{i}") == b"during-%d" % i * 40
