"""Background-task plane: conveyor workers + resource-broker quotas +
the stall/step test seam; compaction runs off the commit path while
scans proceed (VERDICT r4 item 8; reference tx/conveyor/service.h:73,
resource_broker.h, ICSController hooks/abstract.h:49)."""

import threading
import time

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.runtime.conveyor import (
    Conveyor,
    ConveyorController,
    ResourceBroker,
)
from ydb_tpu.ssa.ops import Agg
from ydb_tpu.ssa.program import AggSpec, GroupByStep, Program
from ydb_tpu.tx.coordinator import Coordinator
from ydb_tpu.tx.sharded import ShardedTable

SCHEMA = dtypes.schema(("id", dtypes.INT64, False), ("v", dtypes.INT64))
COUNT = Program((GroupByStep(keys=(), aggs=(
    AggSpec(Agg.COUNT_ALL, None, "n"),
    AggSpec(Agg.SUM, "v", "s"),
)),))


def test_broker_quota_limits_concurrency():
    broker = ResourceBroker(quotas={"compaction": 2})
    conv = Conveyor(workers=4, broker=broker)
    peak = [0]
    cur = [0]
    lock = threading.Lock()

    def job():
        with lock:
            cur[0] += 1
            peak[0] = max(peak[0], cur[0])
        time.sleep(0.05)
        with lock:
            cur[0] -= 1

    hs = [conv.submit("compaction", job) for _ in range(6)]
    for h in hs:
        h.wait(10)
    conv.shutdown()
    assert peak[0] <= 2


def test_stall_step_resume():
    ctl = ConveyorController()
    conv = Conveyor(workers=2, controller=ctl)
    ctl.stall()
    ran = []
    hs = [conv.submit("q", ran.append, i) for i in range(3)]
    time.sleep(0.1)
    assert ran == []  # stalled: nothing executes
    ctl.step(1)
    # either queued task may take the single step token
    deadline = time.time() + 10
    while not ran and time.time() < deadline:
        time.sleep(0.01)
    time.sleep(0.1)
    assert len(ran) == 1  # exactly one stepped through
    ctl.resume()
    for h in hs:
        h.wait(10)
    assert sorted(ran) == [0, 1, 2]
    conv.shutdown()


def test_task_error_surfaces_via_handle():
    conv = Conveyor(workers=1)

    def boom():
        raise RuntimeError("background failure")

    h = conv.submit("q", boom)
    with pytest.raises(RuntimeError, match="background failure"):
        h.wait(10)
    conv.shutdown()


def test_scans_proceed_while_compaction_stalled():
    """The ICSController-style contract: with background compaction
    STALLED on the conveyor, foreground scans and inserts keep working;
    after resume the compaction applies without changing results."""
    from ydb_tpu.engine.shard import ShardConfig

    store = MemBlobStore()
    coord = Coordinator(MemBlobStore())
    t = ShardedTable("t", SCHEMA, store, coord, n_shards=2,
                     pk_column="id", upsert=True,
                     config=ShardConfig(compact_portion_threshold=4))
    for i in range(6):
        t.insert({"id": np.arange(i * 50, i * 50 + 50, dtype=np.int64),
                  "v": np.full(50, i, dtype=np.int64)})
    portions_before = sum(len(s.visible_portions()) for s in t.shards)
    assert portions_before >= 6

    ctl = ConveyorController()
    conv = Conveyor(workers=2, controller=ctl)
    ctl.stall()
    handles = t.run_background(conveyor=conv)
    time.sleep(0.05)

    # compaction is queued but stalled: scans and inserts proceed
    res = t.scan(COUNT)
    assert int(res.cols["n"][0][0]) == 300
    t.insert({"id": np.arange(300, 350, dtype=np.int64),
              "v": np.full(50, 9, dtype=np.int64)})
    res = t.scan(COUNT)
    assert int(res.cols["n"][0][0]) == 350
    assert sum(len(s.visible_portions()) for s in t.shards) > \
        portions_before  # nothing compacted yet

    ctl.resume()
    for h in handles:
        h.wait(30)
    conv.wait_idle()
    conv.shutdown()

    # compaction applied off-path; results unchanged, fewer portions
    res = t.scan(COUNT)
    assert int(res.cols["n"][0][0]) == 350
    assert sum(len(s.visible_portions()) for s in t.shards) < \
        portions_before
