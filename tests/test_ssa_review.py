"""Regression tests for review findings on the SSA layer."""

import numpy as np

from ydb_tpu import dtypes
from ydb_tpu.blocks import DictionarySet, TableBlock
from ydb_tpu.ssa import (
    Agg,
    AggSpec,
    AssignStep,
    Call,
    Col,
    FilterStep,
    GroupByStep,
    Op,
    Program,
    SortStep,
    compile_program,
)
from ydb_tpu.ssa.program import lit


def _block(**cols):
    sch = []
    arrays = {}
    validity = {}
    for name, spec in cols.items():
        arr, t = spec[0], spec[1]
        sch.append((name, t))
        arrays[name] = np.asarray(arr)
        if len(spec) > 2:
            validity[name] = np.asarray(spec[2])
    return TableBlock.from_numpy(arrays, dtypes.schema(*sch), validity or None)


def test_decimal_vs_float_literal_compare():
    blk = _block(price=([4, 6, 100], dtypes.decimal(2)))  # 0.04,0.06,1.00
    prog = Program((FilterStep(Call(Op.LT, Col("price"), lit(0.05))),))
    out = compile_program(prog, blk.schema)(blk)
    np.testing.assert_array_equal(out.to_numpy()["price"], [4])


def test_min_max_string_by_rank_not_id():
    dicts = DictionarySet()
    ids = dicts.for_column("s").encode([b"zebra", b"apple", b"zebra"])
    blk = _block(s=(ids, dtypes.STRING), g=([1, 1, 1], dtypes.INT64))
    prog = Program((
        GroupByStep(keys=("g",), aggs=(
            AggSpec(Agg.MIN, "s", "lo"),
            AggSpec(Agg.MAX, "s", "hi"),
        )),
    ))
    out = compile_program(prog, blk.schema, dicts, key_spaces={"g": 2})(blk)
    res = out.to_numpy()
    assert dicts["s"].values[int(res["lo"][0])] == b"apple"
    assert dicts["s"].values[int(res["hi"][0])] == b"zebra"


def test_sort_desc_nulls_last():
    blk = _block(x=([5, 0, 3, 7], dtypes.INT64, [True, False, True, True]))
    prog = Program((SortStep(keys=("x",), descending=(True,)),))
    out = compile_program(prog, blk.schema)(blk)
    res = out.to_numpy()
    valid = out.validity_numpy()
    np.testing.assert_array_equal(res["x"][:3], [7, 5, 3])
    assert not valid["x"][3]


def test_sort_desc_bool_key():
    blk = _block(b=([True, False, True], dtypes.BOOL))
    prog = Program((SortStep(keys=("b",), descending=(True,)),))
    out = compile_program(prog, blk.schema)(blk)
    np.testing.assert_array_equal(out.to_numpy()["b"], [True, True, False])


def test_null_group_not_split_by_garbage():
    # nullable computed column: garbage under invalid slots must not split
    # the NULL group
    blk = _block(
        a=([10, 20, 7], dtypes.INT64),
        b=([0, 0, 7], dtypes.INT64),
    )
    prog = Program((
        AssignStep("q", Call(Op.DIV, Col("a"), Col("b"))),  # null, null, 1
        GroupByStep(keys=("q",), aggs=(AggSpec(Agg.COUNT_ALL, None, "n"),)),
    ))
    out = compile_program(prog, blk.schema)(blk)
    assert int(out.length) == 2
    res = out.to_numpy()
    assert sorted(res["n"].tolist()) == [1, 2]


def test_group_by_computed_column():
    blk = _block(d=([0, 18262, 18300], dtypes.DATE))
    prog = Program((
        AssignStep("y", Call(Op.YEAR, Col("d"))),
        GroupByStep(keys=("y",), aggs=(AggSpec(Agg.COUNT_ALL, None, "n"),)),
    ))
    out = compile_program(prog, blk.schema)(blk)
    res = out.to_numpy()
    assert int(out.length) == 2
    np.testing.assert_array_equal(sorted(res["y"].tolist()), [1970, 2020])


def test_sorted_groupby_no_silent_drop():
    n = 100  # 100 distinct keys, no explicit cap: all must survive
    blk = _block(k=(np.arange(n) * 13 % 997, dtypes.INT64))
    prog = Program((
        GroupByStep(keys=("k",), aggs=(AggSpec(Agg.COUNT_ALL, None, "n"),)),
    ))
    out = compile_program(prog, blk.schema)(blk)
    assert int(out.length) == n


def test_keyless_aggregate_on_empty_selection():
    blk = _block(v=([1, 2, 3], dtypes.INT64))
    prog = Program((
        FilterStep(Call(Op.GT, Col("v"), lit(100))),
        GroupByStep(keys=(), aggs=(
            AggSpec(Agg.COUNT_ALL, None, "n"),
            AggSpec(Agg.COUNT, "v", "c"),
            AggSpec(Agg.SUM, "v", "s"),
        )),
    ))
    out = compile_program(prog, blk.schema)(blk)
    assert int(out.length) == 1
    res, valid = out.to_numpy(), out.validity_numpy()
    assert res["n"][0] == 0 and valid["n"][0]
    assert res["c"][0] == 0 and valid["c"][0]
    assert not valid["s"][0]  # SUM over empty => NULL


def test_integer_div_mod_truncate_toward_zero():
    blk = _block(
        a=([-7, 7, -7, 7], dtypes.INT64),
        b=([2, -2, -2, 2], dtypes.INT64),
    )
    prog = Program((
        AssignStep("q", Call(Op.DIV, Col("a"), Col("b"))),
        AssignStep("r", Call(Op.MOD, Col("a"), Col("b"))),
    ))
    out = compile_program(prog, blk.schema)(blk)
    res = out.to_numpy()
    np.testing.assert_array_equal(res["q"], [-3, -3, 3, 3])
    np.testing.assert_array_equal(res["r"], [-1, 1, -1, 1])

    from ydb_tpu.engine.oracle import OracleTable, run_oracle

    ora = run_oracle(prog, OracleTable.from_block(blk))
    np.testing.assert_array_equal(ora.cols["q"][0], [-3, -3, 3, 3])
    np.testing.assert_array_equal(ora.cols["r"][0], [-1, 1, -1, 1])
