"""Column statistics subsystem tests: sketch error bounds and merge
algebra, zone-map semantics + portion header round-trips (v0/v1), scan
pruning bit-identity (incl. the upsert shadow hazard and the
filter-skip fast path), the StatisticsAggregator's refresh/restore,
cost-model tier choice, and the DQ build-side selection."""

import numpy as np
import pytest

from ydb_tpu import dtypes
from ydb_tpu import stats as stats_mod
from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.engine.portion import (
    PortionChunkReader,
    PortionMeta,
    column_stats,
    read_portion_blob,
    write_portion_blob,
)
from ydb_tpu.engine.shard import ColumnShard, ShardConfig
from ydb_tpu.ssa import Agg, AggSpec, Call, Col, FilterStep, GroupByStep, Op
from ydb_tpu.ssa.program import DictPredicate, Program, ProjectStep, lit
from ydb_tpu.stats.aggregator import StatisticsAggregator
from ydb_tpu.stats.sketch import ColumnSketch, CountMinSketch, HyperLogLog
from ydb_tpu.stats import cost, zonemap
from ydb_tpu.stats.zonemap import Pred


@pytest.fixture
def stats_on():
    stats_mod.STATS_FORCE = True
    yield
    stats_mod.STATS_FORCE = None


def _force(flag):
    stats_mod.STATS_FORCE = flag


# ---------------- sketches ----------------


def test_hll_ndv_relative_error_across_distributions():
    rng = np.random.default_rng(7)
    cases = {
        "uniform": rng.integers(0, 20000, 100_000),
        "all_distinct": np.arange(50_000),
        "all_equal": np.zeros(50_000, dtype=np.int64),
        "skewed": rng.zipf(1.3, 100_000) % 100_000,
        "floats": rng.normal(size=30_000).round(3),
    }
    for name, vals in cases.items():
        h = HyperLogLog()
        h.add_many(vals)
        true = len(np.unique(vals))
        rel = abs(h.estimate() - true) / max(true, 1)
        assert rel < 0.10, f"{name}: rel err {rel:.3f} (true {true})"


def test_cms_error_bounds_on_skewed_data():
    rng = np.random.default_rng(3)
    vals = rng.zipf(1.5, 100_000) % 5000
    c = CountMinSketch()
    c.add_many(vals)
    counts = np.bincount(vals)
    eps_bound = int(np.e / c.width * len(vals)) + 1
    for v in list(range(20)) + [4999]:
        true = int(counts[v]) if v < len(counts) else 0
        est = c.estimate(v)
        assert est >= true  # count-min never underestimates
        assert est <= true + eps_bound


def test_merge_associative_commutative_and_lossless():
    rng = np.random.default_rng(9)
    parts = [rng.integers(0, 5000, 30_000) for _ in range(3)]
    singles_h = []
    singles_c = []
    for p in parts:
        h, c = HyperLogLog(), CountMinSketch()
        h.add_many(p)
        c.add_many(p)
        singles_h.append(h)
        singles_c.append(c)
    a, b, c3 = singles_h
    left = a.merge(b).merge(c3)
    right = a.merge(b.merge(c3))
    swapped = c3.merge(a).merge(b)
    assert np.array_equal(left.registers, right.registers)
    assert np.array_equal(left.registers, swapped.registers)
    one = HyperLogLog()
    one.add_many(np.concatenate(parts))
    assert np.array_equal(left.registers, one.registers)  # lossless fold
    ca, cb, cc = singles_c
    assert np.array_equal(ca.merge(cb).merge(cc).table,
                          cc.merge(ca.merge(cb)).table)


def test_sketch_json_roundtrip():
    sk = ColumnSketch()
    sk.observe(np.asarray([1, 2, 2, 3]),
               np.asarray([True, True, True, False]))
    back = ColumnSketch.from_json(sk.to_json())
    assert back.rows == 4 and back.nulls == 1
    assert (back.vmin, back.vmax) == (1, 2)
    assert np.array_equal(back.hll.registers, sk.hll.registers)
    assert np.array_equal(back.cms.table, sk.cms.table)


# ---------------- zone maps + column_stats ----------------


def test_column_stats_dtype_aware():
    # floats keep float bounds (the old int() cast truncated them)
    fmin, fmax = column_stats(np.asarray([0.5, 2.25, -1.5]))
    assert (fmin, fmax) == (-1.5, 2.25)
    assert isinstance(fmin, float)
    # ints (dict ids, scaled decimals) stay ints
    imin, imax = column_stats(np.asarray([150, 25], dtype=np.int64))
    assert (imin, imax) == (25, 150) and isinstance(imin, int)
    # validity excludes NULL slots from the bounds
    vmin, vmax = column_stats(np.asarray([7, 99, 1]),
                              np.asarray([True, False, True]))
    assert (vmin, vmax) == (1, 7)
    assert column_stats(np.asarray([], dtype=np.int64)) == (None, None)


def test_match_zone_trichotomy():
    z = [10, 20, 0]
    assert zonemap.match_zone(z, Pred("c", "eq", 25)) == "none"
    assert zonemap.match_zone(z, Pred("c", "eq", 15)) == "some"
    assert zonemap.match_zone([15, 15, 0], Pred("c", "eq", 15)) == "all"
    assert zonemap.match_zone(z, Pred("c", "lt", 10)) == "none"
    assert zonemap.match_zone(z, Pred("c", "lt", 21)) == "all"
    assert zonemap.match_zone(z, Pred("c", "ge", 10)) == "all"
    assert zonemap.match_zone(z, Pred("c", "gt", 20)) == "none"
    assert zonemap.match_zone(z, Pred("c", "in", (1, 2))) == "none"
    assert zonemap.match_zone(z, Pred("c", "in", (15,))) == "some"
    # NULLs block 'all' (a NULL row fails every comparison) but not
    # 'none'
    zn = [10, 20, 3]
    assert zonemap.match_zone(zn, Pred("c", "ge", 5)) == "some"
    assert zonemap.match_zone(zn, Pred("c", "gt", 20)) == "none"
    # all-NULL zone: no row can match anything
    assert zonemap.match_zone([None, None, 8], Pred("c", "eq", 1)) == "none"
    # unknown zone / NaN bounds: always read
    assert zonemap.match_zone(None, Pred("c", "eq", 1)) == "some"
    assert zonemap.match_zone([float("nan"), float("nan"), 0],
                              Pred("c", "lt", 0)) == "some"
    assert zonemap.match_zone(z, Pred("c", "never")) == "none"


def test_extract_predicates_shapes():
    schema = dtypes.schema(("a", dtypes.INT64), ("b", dtypes.decimal(2)),
                           ("s", dtypes.STRING))
    from ydb_tpu.blocks.dictionary import DictionarySet

    dicts = DictionarySet()
    d = dicts.for_column("s")
    d.add(b"x")
    d.add(b"y")
    prog = Program((
        FilterStep(Call(Op.AND,
                        Call(Op.GE, Col("a"), lit(5)),
                        Call(Op.GT, lit(9), Col("a")))),  # flipped: a < 9
        FilterStep(DictPredicate("s", "eq", b"y")),
        FilterStep(Call(Op.IN_SET, Col("a"), lit(1), lit(2))),
        GroupByStep(("a",), (AggSpec(Agg.COUNT_ALL, None, "n"),)),
        # after the group-by: must NOT become a pruning predicate
        FilterStep(Call(Op.GE, Col("n"), lit(1))),
    ))
    preds, full = zonemap.extract_predicates(prog, schema, dicts)
    got = {(p.column, p.op, p.value) for p in preds}
    assert got == {("a", "ge", 5), ("a", "lt", 9), ("s", "eq", 1),
                   ("a", "in", (1, 2))}
    assert full == {0, 1, 2}
    # decimal literals land in the column's scaled physical domain
    prog2 = Program((FilterStep(Call(Op.GE, Col("b"),
                                     lit(3.5, dtypes.DOUBLE))),))
    (p,), _ = zonemap.extract_predicates(prog2, schema)
    assert p.value == 350.0
    # a column shadowed by an assign no longer describes stored bytes
    from ydb_tpu.ssa.program import AssignStep

    prog3 = Program((
        AssignStep("a", Call(Op.ADD, Col("a"), lit(1))),
        FilterStep(Call(Op.GE, Col("a"), lit(5))),
    ))
    preds3, full3 = zonemap.extract_predicates(prog3, schema)
    assert preds3 == [] and full3 == set()
    # an absent dictionary literal is provably constant-false
    prog4 = Program((FilterStep(DictPredicate("s", "eq", b"zzz")),))
    (p4,), _ = zonemap.extract_predicates(prog4, schema, dicts)
    assert p4.op == "never"


# ---------------- portion headers: v0 + v1 round-trip ----------------


def _cols(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    cols = {
        "pk": np.arange(n, dtype=np.int64),
        "f": rng.normal(size=n),
        "d": rng.integers(0, 10**4, n).astype(np.int64),
    }
    validity = {"d": rng.random(n) > 0.1}
    return cols, validity


def test_header_v1_zones_and_v0_compat():
    store = MemBlobStore()
    cols, validity = _cols()
    write_portion_blob(store, "b1", cols, validity, chunk_rows=256,
                       pk_column="pk")
    rd = PortionChunkReader(store, "b1")
    assert rd.version == 1
    meta = rd.chunk_meta(0)
    assert meta["pk_min"] == 0 and meta["pk_max"] == 255
    z = meta["zones"]
    assert z["pk"][:2] == [0, 255]
    assert isinstance(z["f"][0], float)  # dtype-aware, not int-cast
    assert z["d"][2] > 0  # null counts recorded
    # v0 write (stats off) reads identically, just without zones
    write_portion_blob(store, "b0", cols, validity, chunk_rows=256,
                       pk_column="pk", stats=False)
    rd0 = PortionChunkReader(store, "b0")
    assert rd0.version == 0
    assert "zones" not in rd0.chunk_meta(0)
    c1, v1 = read_portion_blob(store, "b1")
    c0, v0 = read_portion_blob(store, "b0")
    for name in cols:
        assert np.array_equal(c1[name], c0[name])
        assert np.array_equal(v1.get(name, True), v0.get(name, True))


def test_portion_meta_json_roundtrip_with_and_without_zones():
    m = PortionMeta(1, "b", 10, commit_snap=2,
                    zones={"a": [1, 5, 0]})
    back = PortionMeta.from_json(m.to_json())
    assert back.zones == {"a": [1, 5, 0]}
    # v0 metadata (pre-stats checkpoints) still loads
    legacy = {"portion_id": 1, "blob_id": "b", "num_rows": 10,
              "commit_snap": 2}
    assert PortionMeta.from_json(legacy).zones is None


# ---------------- shard scan pruning ----------------


SCHEMA = dtypes.schema(
    ("id", dtypes.INT64, False),
    ("ts", dtypes.INT64, False),
    ("val", dtypes.INT64),
)


def _shard(upsert=False, chunk_rows=128):
    return ColumnShard(
        "s1", SCHEMA, MemBlobStore(), pk_column="id", upsert=upsert,
        config=ShardConfig(compact_portion_threshold=10**9,
                           portion_chunk_rows=chunk_rows))


def _fill(shard, commits=4, per=512, seed=1):
    rng = np.random.default_rng(seed)
    for c in range(commits):
        base = c * per
        shard.commit([shard.write(
            {"id": (base + np.arange(per)).astype(np.int64),
             "ts": (base + np.arange(per)).astype(np.int64),
             "val": rng.integers(0, 100, per).astype(np.int64)},
            {"val": rng.random(per) > 0.05},
        )])
    return commits * per


def _table(res):
    order = np.argsort(np.asarray(res.column(res.schema.names[0])))
    out = {}
    for name, (v, ok) in res.cols.items():
        v, ok = np.asarray(v), np.asarray(ok)
        out[name] = (np.where(ok, v, 0)[order], ok[order])
    return out


def _assert_same(a, b):
    ta, tb = _table(a), _table(b)
    assert set(ta) == set(tb)
    for name in ta:
        assert np.array_equal(ta[name][0], tb[name][0]), name
        assert np.array_equal(ta[name][1], tb[name][1]), name


def test_selective_scan_prunes_and_stays_bit_identical():
    shard = _shard()
    n = _fill(shard)
    prog = Program((
        FilterStep(Call(Op.AND,
                        Call(Op.GE, Col("ts"), lit(n // 2)),
                        Call(Op.LT, Col("ts"), lit(n // 2 + 100)))),
        GroupByStep((), (AggSpec(Agg.COUNT_ALL, None, "n"),
                         AggSpec(Agg.SUM, "val", "s"),
                         AggSpec(Agg.MIN, "ts", "lo"))),
    ))
    _force(True)
    try:
        on = shard.scan(prog)
        p = dict(shard.last_scan_pruning)
    finally:
        _force(None)
    _force(False)
    try:
        off = shard.scan(prog)
        p_off = dict(shard.last_scan_pruning)
    finally:
        _force(None)
    _assert_same(on, off)
    assert int(np.asarray(on.column("n"))[0]) == 100
    # >= 2x fewer chunk reads on the <= 10% selectivity predicate
    assert p["chunks_read"] * 2 <= p_off["chunks_read"]
    assert p["chunks_skipped"] + p["portions_skipped"] > 0
    assert p_off["chunks_skipped"] == 0
    # cumulative counters surfaced for the sys view
    assert shard.pruning_totals["scans"] == 2


def test_filter_skip_fast_path_drops_proven_filters():
    shard = _shard()
    n = _fill(shard)
    # NOT NULL column predicate every row satisfies -> droppable
    prog = Program((
        FilterStep(Call(Op.GE, Col("ts"), lit(0))),
        GroupByStep((), (AggSpec(Agg.COUNT_ALL, None, "n"),)),
    ))
    _force(True)
    try:
        on = shard.scan(prog)
        p = dict(shard.last_scan_pruning)
    finally:
        _force(None)
    _force(False)
    try:
        off = shard.scan(prog)
    finally:
        _force(None)
    assert p["filters_dropped"] == 1
    assert p["chunks_fastpath"] == p["chunks_read"] > 0
    _assert_same(on, off)
    assert int(np.asarray(on.column("n"))[0]) == n
    # a NULLABLE column predicate must NOT be dropped (NULL rows fail
    # the filter even when the value bounds all match)
    prog2 = Program((
        FilterStep(Call(Op.GE, Col("val"), lit(0))),
        GroupByStep((), (AggSpec(Agg.COUNT_ALL, None, "n"),)),
    ))
    _force(True)
    try:
        on2 = shard.scan(prog2)
        p2 = dict(shard.last_scan_pruning)
    finally:
        _force(None)
    assert p2["filters_dropped"] == 0
    assert int(np.asarray(on2.column("n"))[0]) < n


def test_upsert_shadowing_defeats_naive_pruning():
    """A newer row version that FAILS the filter shadows an older
    version that passes: pruning the newer portion would resurrect the
    old row. The stats path must keep upsert results identical."""
    shard = _shard(upsert=True)
    ids = np.arange(64, dtype=np.int64)
    shard.commit([shard.write(
        {"id": ids, "ts": ids, "val": np.full(64, 10, dtype=np.int64)})])
    # overwrite the same PKs with values OUTSIDE the filter range
    shard.commit([shard.write(
        {"id": ids, "ts": ids, "val": np.full(64, 999, dtype=np.int64)})])
    prog = Program((
        FilterStep(Call(Op.LE, Col("val"), lit(50))),
        GroupByStep((), (AggSpec(Agg.COUNT_ALL, None, "n"),)),
    ))
    _force(True)
    try:
        on = shard.scan(prog)
    finally:
        _force(None)
    _force(False)
    try:
        off = shard.scan(prog)
    finally:
        _force(None)
    # newest-wins: every visible row has val=999, nothing matches
    assert int(np.asarray(on.column("n"))[0]) == 0
    _assert_same(on, off)


def test_visible_portions_value_preds_generalize_pk_path():
    shard = _shard()
    _fill(shard, commits=4, per=256)
    # PK special case still prunes (the legacy spelling)
    assert len(shard.visible_portions(pk_range=(900, None))) == 1
    # general value predicate on a non-PK column through zone maps
    kept = shard.visible_portions(preds=[Pred("ts", "ge", 900)])
    assert len(kept) == 1
    kept2 = shard.visible_portions(preds=[Pred("val", "gt", 10**9)])
    assert kept2 == []
    assert len(shard.visible_portions(preds=[Pred("c", "never")])) == 0


def test_v0_portions_scan_unpruned_but_correct(stats_on):
    """Portions written before zone maps (no meta.zones, v0 headers)
    must keep scanning correctly with stats enabled — conservative
    unpruned reads."""
    shard = _shard()
    n = _fill(shard, commits=2, per=256)
    for m in shard.visible_portions():
        m.zones = None  # simulate pre-stats metadata
    prog = Program((
        FilterStep(Call(Op.GE, Col("ts"), lit(n - 10))),
        GroupByStep((), (AggSpec(Agg.COUNT_ALL, None, "n"),)),
    ))
    assert int(np.asarray(shard.scan(prog).column("n"))[0]) == 10


def test_group_key_bounds_from_zones(stats_on):
    """Integer group keys gain exact dense-tier bounds from zone maps;
    results match the statless plan."""
    shard = _shard()
    rng = np.random.default_rng(5)
    for c in range(3):
        per = 300
        shard.commit([shard.write(
            {"id": (c * per + np.arange(per)).astype(np.int64),
             "ts": (c * per + np.arange(per)).astype(np.int64),
             "val": rng.integers(0, 7, per).astype(np.int64)})])
    prog = Program((
        GroupByStep(("val",), (AggSpec(Agg.COUNT_ALL, None, "n"),)),
    ))
    on = shard.scan(prog)
    _force(False)
    try:
        off = shard.scan(prog)
    finally:
        _force(True)
    _assert_same(on, off)
    assert int(np.asarray(on.column("n")).sum()) == 900


# ---------------- compiler: NDV tier choice + capacity ----------------


def test_group_est_demotes_dense_to_sorted_identically():
    from ydb_tpu.blocks.block import TableBlock
    from ydb_tpu.ssa.compiler import compile_program

    import jax

    rng = np.random.default_rng(2)
    schema = dtypes.schema(("a", dtypes.INT64), ("b", dtypes.INT64),
                           ("v", dtypes.INT64))
    n = 4096
    cols = {
        "a": rng.integers(0, 50, n).astype(np.int64),
        "b": (rng.integers(0, 50, n) // 10 * 10).astype(np.int64),
        "v": rng.integers(0, 100, n).astype(np.int64),
    }
    prog = Program((
        GroupByStep(("a", "b"), (AggSpec(Agg.SUM, "v", "s"),
                                 AggSpec(Agg.COUNT_ALL, None, "n"))),
    ))
    spaces = {"a": 50, "b": 50}
    blk = TableBlock.from_numpy(cols, schema)
    outs = {}
    for label, est in (("dense", None), ("sorted", 60.0)):
        cp = compile_program(prog, schema, key_spaces=spaces,
                             group_est=est)
        aux = {k: jax.numpy.asarray(v) for k, v in cp.aux.items()}
        outs[label] = cp.run(blk, aux)
    assert outs["dense"] is not None
    layouts = {}
    for label, est in (("dense", None), ("sorted", 60.0)):
        cp = compile_program(prog, schema, key_spaces=spaces,
                             group_est=est)
        layouts[label] = cp.group_layout[0]
    assert layouts["dense"] == "dense"
    assert layouts["sorted"] == "compact"  # NDV demoted the tier

    def rows(blk):
        m = int(blk.length)
        key = [np.asarray(blk.columns["a"].data[:m]),
               np.asarray(blk.columns["b"].data[:m])]
        order = np.lexsort((key[1], key[0]))
        return {n_: np.asarray(blk.columns[n_].data[:m])[order]
                for n_ in ("a", "b", "s", "n")}
    ra, rb = rows(outs["dense"]), rows(outs["sorted"])
    for name in ra:
        assert np.array_equal(ra[name], rb[name]), name


def test_choose_group_tier_matches_truth_on_bench_shapes():
    # kernelbench shape: 16 groups, HLL-estimated
    for true_groups in (7, 16, 512, 5000):
        vals = np.arange(true_groups)
        h = HyperLogLog()
        h.add_many(vals)
        assert cost.choose_group_tier(h.estimate()) == \
            cost.choose_group_tier(true_groups)


def test_cost_selectivity_and_group_count():
    st = cost.TableStats(rows=1000, columns={
        "a": cost.ColumnStats(ndv=100, nulls=0, rows=1000, vmin=0,
                              vmax=999),
        "b": cost.ColumnStats(ndv=10, nulls=100, rows=1000, vmin=0,
                              vmax=9),
    })
    assert cost.pred_selectivity(Pred("a", "eq", 5), st) == \
        pytest.approx(0.01)
    # band predicate intersects exactly instead of multiplying
    band = [Pred("a", "ge", 0), Pred("a", "lt", 100)]
    assert cost.conj_selectivity(band, st) == pytest.approx(0.1, rel=0.1)
    assert cost.pred_selectivity(Pred("c", "never"), st) == 0.0
    g = cost.estimate_group_count(("a", "b"), st)
    assert g == 1000  # capped by row count (100 * 11 > rows)
    assert cost.estimate_group_count(("b",), st) == 11  # NULL group


# ---------------- aggregator ----------------


def test_aggregator_refresh_ndv_and_restore():
    store = MemBlobStore()
    shard = ColumnShard("s1", SCHEMA, store, pk_column="id",
                        config=ShardConfig(
                            compact_portion_threshold=10**9,
                            portion_chunk_rows=128))
    rng = np.random.default_rng(4)
    for c in range(3):
        per = 500
        shard.commit([shard.write(
            {"id": (c * per + np.arange(per)).astype(np.int64),
             "ts": (c * per + np.arange(per)).astype(np.int64),
             "val": rng.integers(0, 200, per).astype(np.int64)},
            {"val": rng.random(per) > 0.1})])
    agg = StatisticsAggregator(store=store)
    st = agg.refresh_table("t", [shard])
    assert st.rows == 1500
    cs = st.columns["id"]
    assert abs(cs.ndv - 1500) / 1500 < 0.10
    assert st.columns["val"].nulls > 0
    assert st.columns["val"].vmin >= 0
    # restore: a NEW aggregator on the same store serves the snapshot
    # before any refresh runs (tablet WAL machinery)
    agg2 = StatisticsAggregator(store=store)
    st2 = agg2.table_stats("t")
    assert st2 is not None and st2.rows == 1500
    assert st2.columns["id"].ndv == cs.ndv
    # incremental: second refresh recomputes nothing (portion cache)
    before = len(agg._portions)
    agg.refresh_table("t", [shard])
    assert len(agg._portions) == before
    agg.forget("t")
    assert StatisticsAggregator(store=store).table_stats("t") is None


def test_drop_recreate_table_does_not_serve_stale_sketches():
    """A re-created same-name table reuses shard AND portion ids: the
    aggregator's per-portion sketch cache must not serve the dropped
    table's sketches as the new table's statistics."""
    from ydb_tpu.kqp.session import Cluster

    c = Cluster(n_shards=1)
    s = c.session()
    s.execute("create table t (a bigint not null, b bigint)")
    s.execute("insert into t (a, b) values " + ",".join(
        f"({i}, 1)" for i in range(50)))  # b: 1 distinct value
    c.run_background()
    assert c.stats.table_stats("t").columns["b"].ndv == 1
    s.execute("drop table t")
    s.execute("create table t (a bigint not null, b bigint)")
    s.execute("insert into t (a, b) values " + ",".join(
        f"({i}, {i})" for i in range(50)))  # b: 50 distinct values
    c.run_background()
    cs = c.stats.table_stats("t").columns["b"]
    assert abs(cs.ndv - 50) / 50 < 0.2, cs.ndv


def test_aggregator_steady_state_refresh_is_cached():
    """An unchanged portion set must serve the cached TableStats object
    (no re-merge, no WAL rewrite) until a commit changes it."""
    store = MemBlobStore()
    shard = ColumnShard("s1", SCHEMA, store, pk_column="id",
                        config=ShardConfig(
                            compact_portion_threshold=10**9))
    shard.commit([shard.write(
        {"id": np.arange(10, dtype=np.int64),
         "ts": np.arange(10, dtype=np.int64),
         "val": np.arange(10, dtype=np.int64)})])
    agg = StatisticsAggregator(store=store)
    st1 = agg.refresh_table("t", [shard])
    committed = agg.executor.counters["tx_committed"]
    assert agg.refresh_table("t", [shard]) is st1  # cached object
    assert agg.executor.counters["tx_committed"] == committed
    shard.commit([shard.write(
        {"id": np.arange(10, 20, dtype=np.int64),
         "ts": np.arange(10, 20, dtype=np.int64),
         "val": np.arange(10, dtype=np.int64)})])
    st2 = agg.refresh_table("t", [shard])
    assert st2 is not st1 and st2.rows == 20


def test_aggregator_background_thread_lifecycle():
    import threading

    agg = StatisticsAggregator()
    fired = threading.Event()
    agg.start(0.01, fired.set)
    assert fired.wait(2.0)
    agg.stop()
    assert agg._thread is None


# ---------------- SQL path + sysviews ----------------


def test_sql_scan_pruning_bit_identical_and_sysviews():
    from ydb_tpu.kqp.session import Cluster

    c = Cluster(n_shards=2)
    s = c.session()
    s.execute("create table ev (id bigint not null, ts bigint not null,"
              " tag string, val int) with (shards = 2)")
    for i in range(3):
        vals = ",".join(
            f"({i * 100 + j}, {i * 100 + j}, 't{j % 3}', {j})"
            for j in range(50))
        s.execute(f"insert into ev (id, ts, tag, val) values {vals}")
    c.run_background()  # aggregator refresh rides maintenance
    q = ("select tag, count(*) as n, sum(val) as sv from ev "
         "where ts >= 200 and ts < 230 group by tag order by tag")
    _force(True)
    try:
        on = s.execute(q)
    finally:
        _force(None)
    _force(False)
    try:
        off = s.execute(q)
    finally:
        _force(None)
    assert np.array_equal(np.asarray(on.column("n")),
                          np.asarray(off.column("n")))
    assert np.array_equal(np.asarray(on.column("sv")),
                          np.asarray(off.column("sv")))
    # a dictionary-absent literal is constant-false end to end
    none = s.execute("select count(*) as n from ev where tag = 'zzz'")
    assert int(np.asarray(none.column("n"))[0]) == 0
    # statistics sysview: NDV + null fractions per column
    st = s.execute("select column_name, ndv, rows from sys_statistics "
                   "where table_name = 'ev'")
    assert st.num_rows == 4
    ndv = dict(zip(
        (v.decode() for v in st.dicts["column_name"].decode(
            np.asarray(st.column("column_name")))),
        np.asarray(st.column("ndv")).tolist()))
    assert ndv["tag"] == 3
    assert abs(ndv["id"] - 150) / 150 < 0.1
    # pruning counters sysview exists per shard
    pr = s.execute("select shard, scans from sys_scan_pruning")
    assert pr.num_rows == 2


def test_viewer_statistics_endpoint():
    import json
    import urllib.request

    from ydb_tpu.kqp.session import Cluster
    from ydb_tpu.obs.viewer import Viewer

    c = Cluster(n_shards=1)
    s = c.session()
    s.execute("create table t (a bigint not null, b int)")
    s.execute("insert into t (a, b) values (1, 10), (2, 20), (3, null)")
    v = Viewer(c).start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{v.port}/viewer/json/statistics",
                timeout=10) as r:
            payload = json.loads(r.read())
    finally:
        v.stop()
    cols = {row["column_name"]: row for row in payload["columns"]}
    assert cols["a"]["ndv"] == 3
    assert cols["b"]["null_fraction"] == pytest.approx(1 / 3)
    assert isinstance(payload["pruning"], list)


# ---------------- DQ build-side selection ----------------


def test_dq_build_side_swap_from_estimates():
    from ydb_tpu.engine.scan import ColumnSource
    from ydb_tpu.kqp.dq_lower import execute_plan_dq, plan_to_stages, \
        partition_source
    from ydb_tpu.plan.nodes import ExpandJoin, TableScan, Transform
    from ydb_tpu.runtime.actors import ActorSystem

    rng = np.random.default_rng(6)
    big_n, small_n = 4000, 64
    big = ColumnSource(
        {"k": rng.integers(0, 50, big_n).astype(np.int64),
         "x": rng.integers(0, 100, big_n).astype(np.int64)},
        dtypes.schema(("k", dtypes.INT64), ("x", dtypes.INT64)))
    small = ColumnSource(
        {"k": np.arange(small_n, dtype=np.int64) % 50,
         "y": np.arange(small_n, dtype=np.int64)},
        dtypes.schema(("k", dtypes.INT64), ("y", dtypes.INT64)))
    plan = Transform(
        ExpandJoin(
            TableScan("small", Program((ProjectStep(("k", "y")),))),
            TableScan("big", Program((ProjectStep(("k", "x")),))),
            ("k",), ("k",), ("k", "y"), ("x",)),
        Program((GroupByStep((), (AggSpec(Agg.COUNT_ALL, None, "n"),
                                  AggSpec(Agg.SUM, "x", "sx"),
                                  AggSpec(Agg.SUM, "y", "sy"))),)))

    def estimator(node):
        if isinstance(node, TableScan):
            return float(big_n if node.table == "big" else small_n)
        return None

    # with estimates + swap allowed, the big "build" becomes the probe
    stages = plan_to_stages(plan, estimator=estimator, allow_swap=True)
    join_stage = next(st for st in stages if st.join is not None)
    assert join_stage.join.probe_payload == ("x",)
    baseline = plan_to_stages(plan)
    base_join = next(st for st in baseline if st.join is not None)
    assert base_join.join.probe_payload == ("k", "y")

    sources = {"big": partition_source(big, 2),
               "small": partition_source(small, 2)}
    outs = {}
    for label, kw in (("plain", {}),
                      ("stats", {"estimator": estimator,
                                 "allow_swap": True})):
        outs[label] = execute_plan_dq(
            plan, sources, ActorSystem(node=1), **kw)
    for col in ("n", "sx", "sy"):
        assert np.array_equal(np.asarray(outs["plain"].column(col)),
                              np.asarray(outs["stats"].column(col))), col


def test_kernelbench_pruning_smoke():
    from ydb_tpu.obs import kernelbench

    assert kernelbench.main(
        ["--smoke", "--pruning", "--json"]) == 0
