"""Tablet infrastructure tests: executor boot/replay, MVCC local DB,
state storage quorum, Hive placement + failure recovery, pipes.

Mirrors of the reference's tablet_flat ut shapes + TTestActorRuntime
multi-node tests (SURVEY.md §4 tier 2)."""

import pytest

from ydb_tpu.engine.blobs import MemBlobStore
from ydb_tpu.runtime.actors import Actor
from ydb_tpu.runtime.test_runtime import SimRuntime
from ydb_tpu.tablet.executor import TabletExecutor, Transaction
from ydb_tpu.tablet.hive import (
    CreateTablet, Hive, KillNode, LocalAgent, TabletActor, TabletCreated,
)
from ydb_tpu.tablet.localdb import LocalDb, TableStore
from ydb_tpu.tablet.pipe import PipeClient, PipeSend
from ydb_tpu.tablet.statestorage import StateStorageProxy, StateStorageReplica


# ---------- LocalDb ----------

def test_localdb_mvcc_versions():
    t = TableStore("t")
    t.put(("a",), {"x": 1}, version=1)
    t.put(("a",), {"x": 2}, version=5)
    assert t.get(("a",)) == {"x": 2}
    assert t.get(("a",), version=1) == {"x": 1}
    assert t.get(("a",), version=4) == {"x": 1}
    t.put(("a",), None, version=7)  # erase
    assert t.get(("a",)) is None
    assert t.get(("a",), version=6) == {"x": 2}


def test_localdb_range_and_compact():
    t = TableStore("t")
    for i in range(5):
        t.put((i,), {"v": i}, version=1)
    t.put((2,), {"v": 22}, version=3)
    t.put((3,), None, version=3)
    rows = list(t.range(lo=(1,), hi=(4,)))
    assert rows == [((1,), {"v": 1}), ((2,), {"v": 22})]
    rows_old = list(t.range(lo=(1,), hi=(4,), version=2))
    assert rows_old == [((1,), {"v": 1}), ((2,), {"v": 2}),
                        ((3,), {"v": 3})]
    t.compact(keep_after=3)
    assert t.get((2,)) == {"v": 22}
    assert t.get((3,)) is None
    assert (3,) not in t._chains  # tombstone fully collected


def test_localdb_dump_load_roundtrip():
    db = LocalDb()
    db.apply([("t", (1, "a"), {"v": 1}), ("u", (2,), {"w": 9})], version=4)
    db.apply([("t", (1, "a"), None)], version=6)
    db2 = LocalDb.load(db.dump())
    assert db2.table("t").get((1, "a")) is None
    assert db2.table("t").get((1, "a"), version=5) == {"v": 1}
    assert db2.table("u").get((2,)) == {"w": 9}


# ---------- executor ----------

class PutTx(Transaction):
    def __init__(self, table, key, row):
        self.args = (table, key, row)
        self.completed = False

    def execute(self, txc, tablet):
        txc.put(*self.args)

    def complete(self, tablet):
        self.completed = True


def test_executor_commit_boot_replay():
    store = MemBlobStore()
    ex = TabletExecutor("t1", store)
    for i in range(10):
        tx = ex.execute(PutTx("kv", (i,), {"v": i * 10}))
        assert tx.completed
    # cold boot on a "different node": same store, fresh executor
    ex2 = TabletExecutor.boot("t1", store)
    assert ex2.generation == ex.generation + 1
    for i in range(10):
        assert ex2.db.table("kv").get((i,)) == {"v": i * 10}
    assert ex2.version == ex.version


def test_executor_checkpoint_truncates_log():
    store = MemBlobStore()
    ex = TabletExecutor("t2", store)
    for i in range(5):
        ex.execute(PutTx("kv", (i,), {"v": i}))
    assert len(store.list("tablet/t2/log/")) == 5
    ex.checkpoint()
    assert store.list("tablet/t2/log/") == []
    ex.execute(PutTx("kv", (99,), {"v": 99}))
    ex3 = TabletExecutor.boot("t2", store)
    assert ex3.db.table("kv").get((99,)) == {"v": 99}
    assert ex3.db.table("kv").get((0,)) == {"v": 0}


def test_executor_generation_fencing():
    store = MemBlobStore()
    ex = TabletExecutor("t3", store)
    ex.execute(PutTx("kv", ("k",), {"v": "old"}))
    # a new leader boots (gen+1) and writes
    new_leader = TabletExecutor.boot("t3", store)
    new_leader.execute(PutTx("kv", ("k",), {"v": "new"}))
    # zombie old leader keeps appending to its lower generation
    ex.execute(PutTx("kv", ("k",), {"v": "zombie"}))
    # next boot follows the highest-generation chain only
    ex2 = TabletExecutor.boot("t3", store)
    assert ex2.db.table("kv").get(("k",)) == {"v": "new"}


def test_executor_zombie_checkpoint_is_fenced():
    from ydb_tpu.tablet.executor import FencedError

    store = MemBlobStore()
    ex = TabletExecutor("t4", store)
    ex.execute(PutTx("kv", ("k",), {"v": "old"}))
    new_leader = TabletExecutor.boot("t4", store)
    new_leader.execute(PutTx("kv", ("k",), {"v": "new"}))
    # the fenced-out leader keeps committing, enough to trigger its
    # automatic checkpoint — which must be refused, not written, or the
    # zombie snapshot would outrank the successor's redo records
    with pytest.raises(FencedError):
        for i in range(TabletExecutor.SNAP_EVERY + 1):
            ex.execute(PutTx("kv", (f"z{i}",), {"v": i}))
    ex2 = TabletExecutor.boot("t4", store)
    assert ex2.db.table("kv").get(("k",)) == {"v": "new"}
    assert ex2.db.table("kv").get(("z0",)) is None


def test_executor_boot_skips_zombie_tainted_snapshot():
    store = MemBlobStore()
    ex = TabletExecutor("t5", store)
    ex.execute(PutTx("kv", ("k",), {"v": "old"}))
    new_leader = TabletExecutor.boot("t5", store)
    new_leader.execute(PutTx("kv", ("k",), {"v": "new"}))
    # simulate a zombie snapshot that raced past the fence check: write
    # it directly the way a stale checkpoint would have
    import json
    zsnap = {
        "gen": ex.generation,
        "version": new_leader.version + 5,  # includes zombie writes
        "log_index": ex.log_index,
        "db": ex.db.dump(),
    }
    store.put(
        f"tablet/t5/snap/{zsnap['gen']:08d}.{zsnap['version']:012d}",
        json.dumps(zsnap).encode())
    ex2 = TabletExecutor.boot("t5", store)
    assert ex2.db.table("kv").get(("k",)) == {"v": "new"}


# ---------- cluster: state storage + hive + pipes ----------

class CounterTablet(TabletActor):
    def handle(self, message, reply_to):
        if message[0] == "add":
            amount = message[1]

            class Tx(Transaction):
                def execute(self, txc, tablet):
                    row = txc.get("c", ("v",)) or {"n": 0}
                    txc.put("c", ("v",), {"n": row["n"] + amount})

            self.executor.execute(Tx())
            self.send(reply_to, ("added", self.tablet_id))
        elif message[0] == "get":
            row = self.executor.db.table("c").get(("v",)) or {"n": 0}
            self.send(reply_to, ("value", row["n"], self.self_id.node))


class Probe(Actor):
    def __init__(self):
        super().__init__()
        self.inbox = []

    def receive(self, message, sender):
        self.inbox.append(message)


@pytest.fixture
def cluster():
    rt = SimRuntime(n_nodes=4)
    store = MemBlobStore()
    replicas = [rt.system(n).register(StateStorageReplica())
                for n in (1, 2, 3)]
    proxies = {n: rt.system(n).register(StateStorageProxy(replicas))
               for n in rt.nodes}
    hive_id = rt.system(1).register(Hive())
    factories = {"counter": CounterTablet}
    agents = {}
    for n in (2, 3, 4):
        agents[n] = rt.system(n).register(
            LocalAgent(store, proxies[n], factories, hive=hive_id))
    rt.dispatch()
    return rt, store, proxies, hive_id, agents


def test_hive_creates_and_pipe_routes(cluster):
    rt, store, proxies, hive_id, agents = cluster
    probe = Probe()
    probe_id = rt.system(1).register(probe)
    rt.system(1).send(hive_id, CreateTablet("cnt-1", "counter"),
                      sender=probe_id)
    rt.dispatch()
    created = [m for m in probe.inbox if isinstance(m, TabletCreated)]
    assert len(created) == 1

    pipe = rt.system(1).register(
        PipeClient("cnt-1", proxies[1], probe_id))
    for amount in (5, 7):
        rt.system(1).send(pipe, PipeSend(("add", amount)))
    rt.system(1).send(pipe, PipeSend(("get",)))
    rt.dispatch()
    values = [m for m in probe.inbox
              if isinstance(m, tuple) and m[0] == "value"]
    assert values and values[-1][1] == 12


def test_hive_reboots_tablet_after_node_death(cluster):
    rt, store, proxies, hive_id, agents = cluster
    probe = Probe()
    probe_id = rt.system(1).register(probe)
    rt.system(1).send(hive_id, CreateTablet("cnt-2", "counter"),
                      sender=probe_id)
    rt.dispatch()
    home = [m for m in probe.inbox if isinstance(m, TabletCreated)][0].node

    pipe = rt.system(1).register(
        PipeClient("cnt-2", proxies[1], probe_id))
    rt.system(1).send(pipe, PipeSend(("add", 42)))
    rt.dispatch()

    # kill the hosting node; hive's ping loop detects and reboots
    rt.system(home).send(agents[home], KillNode())
    rt.dispatch()
    rt.system(1).send(pipe, PipeSend(("get",)))

    def got_value():
        return any(isinstance(m, tuple) and m[0] == "value"
                   for m in probe.inbox)

    assert rt.run_until(got_value, max_iterations=200)
    value_msg = [m for m in probe.inbox
                 if isinstance(m, tuple) and m[0] == "value"][-1]
    assert value_msg[1] == 42          # state recovered from blob store
    assert value_msg[2] != home        # now on a different node


def test_localdb_parts_bloom_and_compaction():
    """Memtable/part split (VERDICT r4 missing 8; reference
    flat_part_*.h): writes auto-freeze into page-indexed parts, point
    reads skip non-holding parts via bloom filters, MVCC versions stay
    correct across the memtable/part boundary, and compaction merges
    parts away under the horizon."""
    from ydb_tpu.tablet.localdb import TableStore

    t = TableStore("t", memtable_limit=100)
    for i in range(350):  # 3 auto-freezes + live memtable
        t.put((i,), {"v": i}, version=i + 1)
    assert t.n_parts == 3
    # point reads across parts + memtable
    for i in (0, 99, 100, 250, 349):
        assert t.get((i,)) == {"v": i}
    # bloom: probing absent keys skips parts without page scans
    for i in range(400, 600):
        assert t.get((i,)) is None
    assert t.bloom_negatives() > 0
    # MVCC across the boundary: overwrite a frozen key in the memtable
    t.put((5,), {"v": 999}, version=500)
    assert t.get((5,)) == {"v": 999}
    assert t.get((5,), version=400) == {"v": 5}   # part version visible
    # tombstone in memtable shadows a part row
    t.put((6,), None, version=501)
    assert t.get((6,)) is None
    assert t.get((6,), version=400) == {"v": 6}
    # range merges memtable + parts in key order
    got = [k[0] for k, _r in t.range((3,), (9,))]
    assert got == [3, 4, 5, 7, 8]  # 6 tombstoned
    # dump/load round-trips the merged state
    t2 = TableStore.load("t", t.dump())
    assert t2.get((5,), version=400) == {"v": 5}
    assert t2.get((250,)) == {"v": 250}
    # compaction folds parts and prunes shadowed versions
    t.compact(keep_after=502)
    assert t.n_parts == 0
    assert t.get((5,)) == {"v": 999}
    assert t.get((6,)) is None
    assert len(t._full_chain((5,))) == 1  # shadowed version pruned
