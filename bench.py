"""Benchmark: TPC-H Q1/Q6 scan+filter+aggregate throughput on the device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Config (BASELINE.md config 2): TPC-H Q1 and Q6 at SF (default 10 — ~60M
lineitem rows), executed by the block-streamed columnar engine on the
default JAX device (the real TPU chip under the driver).

Metrics:
  * primary  — Q1 steady-state scan rows/s/chip (data resident in HBM,
    the engine's steady state; the scan reads 7 columns per row).
  * extra.q6_rows_per_sec       — Q6 (filter + global agg) rows/s/chip.
  * extra.ingest_rows_per_sec   — host->HBM transfer included (cold data).
  * extra.hbm_gb_per_sec        — effective HBM read bandwidth of the Q1
    scan (7 x int64/int32 columns), for roofline context.
  * extra.cpu_q1_rows_per_sec   — the CPU baseline actually measured.

Baseline: a tight vectorized single-pass numpy implementation of the same
queries (mask + bincount) on the identical host — an Arrow-compute-class
columnar CPU engine, NOT the repo's interpretive oracle. BASELINE.md
requires the CPU number to be measured, not copied (the reference
publishes none and its 2M-LoC C++ server cannot be built in this image).
Results are cross-checked engine-vs-baseline before timing is reported.

Env knobs: YDB_TPU_BENCH_SF (default 10), YDB_TPU_BENCH_ITERS (default 5),
YDB_TPU_BENCH_BLOCK_ROWS (default 2^21).
"""

import json
import os
import time

import numpy as np


def cpu_q1(li, cutoff):
    """Vectorized single-pass numpy Q1 (the CPU columnar baseline)."""
    m = li["l_shipdate"] <= cutoff
    nls = int(li["l_linestatus"].max()) + 1
    rf = li["l_returnflag"][m].astype(np.int64)
    ls = li["l_linestatus"][m].astype(np.int64)
    gid = rf * nls + ls
    ng = int(gid.max()) + 1
    qty = li["l_quantity"][m]
    price = li["l_extendedprice"][m]
    disc = li["l_discount"][m]
    tax = li["l_tax"][m]
    disc_price = price * (100 - disc)          # scale 4
    charge = disc_price * (100 + tax)          # scale 6
    out = {
        "count": np.bincount(gid, minlength=ng),
    }
    for name, col in (("sum_qty", qty), ("sum_base_price", price),
                      ("sum_disc_price", disc_price),
                      ("sum_charge", charge), ("sum_disc", disc)):
        out[name] = np.bincount(gid, weights=col.astype(np.float64),
                                minlength=ng)
    keep = out["count"] > 0
    out = {k: v[keep] for k, v in out.items()}
    out["gid"] = np.flatnonzero(keep)
    return out, int(m.sum()), nls


def cpu_q6(li, d0, d1):
    m = ((li["l_shipdate"] >= d0) & (li["l_shipdate"] < d1)
         & (li["l_discount"] >= 5) & (li["l_discount"] <= 7)
         & (li["l_quantity"] < 2400))
    return int(np.sum(li["l_extendedprice"][m] * li["l_discount"][m]))


def main():
    sf = float(os.environ.get("YDB_TPU_BENCH_SF", "10"))
    iters = int(os.environ.get("YDB_TPU_BENCH_ITERS", "5"))
    block_rows = int(os.environ.get("YDB_TPU_BENCH_BLOCK_ROWS",
                                    str(1 << 21)))

    import jax

    from ydb_tpu.engine.scan import ColumnSource, ScanExecutor
    from ydb_tpu.workload import tpch

    data = tpch.TpchData(sf=sf, seed=42)
    li = data.tables["lineitem"]
    n_rows = len(li["l_orderkey"])
    src = ColumnSource(
        columns=li, schema=tpch.LINEITEM_SCHEMA, dicts=data.dicts
    )

    ex1 = ScanExecutor(tpch.q1_program(), src, block_rows=block_rows)
    ex6 = ScanExecutor(tpch.q6_program(), src, block_rows=block_rows)
    # one resident block set covering both queries' columns (Q6's are a
    # subset of Q1's); ingest = the host->HBM transfer of those columns
    read_cols = tuple(dict.fromkeys(ex1.read_cols + ex6.read_cols))
    t0 = time.perf_counter()
    blocks = [
        jax.device_put(b) for b in src.blocks(block_rows, read_cols)
    ]
    jax.block_until_ready(blocks)
    ingest_dt = time.perf_counter() - t0
    nbytes = sum(
        c.data.nbytes + c.validity.nbytes
        for b in blocks for c in b.columns.values()
    )

    def run(ex):
        out = ex.finalize([ex.run_block(b) for b in blocks])
        jax.block_until_ready(out)
        return out

    def timed(ex):
        run(ex)  # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            out = run(ex)
        dt = (time.perf_counter() - t0) / iters
        return out, n_rows / dt, dt

    out1, q1_rps, q1_dt = timed(ex1)
    out6, q6_rps, _ = timed(ex6)

    # ---- CPU baseline (vectorized numpy single pass, same data) ----
    cutoff = tpch._days("1998-12-01") - 90
    t0 = time.perf_counter()
    base1, _, nls = cpu_q1(li, cutoff)
    cpu_q1_dt = time.perf_counter() - t0
    cpu_q1_rps = n_rows / cpu_q1_dt
    t0 = time.perf_counter()
    base6 = cpu_q6(li, tpch._days("1994-01-01"), tpch._days("1995-01-01"))
    cpu_q6_dt = time.perf_counter() - t0

    # ---- cross-check engine vs baseline before reporting ----
    res1 = out1.to_numpy()
    n1 = int(out1.length)
    # associate engine rows with baseline rows BY GROUP KEY (same dict
    # ids on both sides), so a value/key misassociation cannot pass
    eng_gid = (res1["l_returnflag"][:n1].astype(np.int64) * nls
               + res1["l_linestatus"][:n1].astype(np.int64))
    eng_order = np.argsort(eng_gid)
    assert np.array_equal(eng_gid[eng_order], base1["gid"]), (
        "engine/baseline group keys differ")
    for eng_col, base_col in (("count_order", "count"),
                              ("sum_qty", "sum_qty"),
                              ("sum_base_price", "sum_base_price"),
                              ("sum_disc_price", "sum_disc_price"),
                              ("sum_charge", "sum_charge")):
        ev = np.asarray(res1[eng_col][:n1], dtype=np.float64)[eng_order]
        assert np.allclose(ev, base1[base_col], rtol=1e-9), (
            f"engine/baseline mismatch on {eng_col}")
    rev = int(np.asarray(out6.to_numpy()["revenue"])[0])
    assert rev == base6, f"Q6 mismatch {rev} != {base6}"

    q1_bytes = sum(
        c.data.nbytes + c.validity.nbytes
        for b in blocks for name, c in b.columns.items()
        if name in ex1.read_cols
    )
    print(json.dumps({
        "metric": f"tpch_q1_sf{sf:g}_scan_rows_per_sec",
        "value": round(q1_rps),
        "unit": "rows/s",
        "vs_baseline": round(q1_rps / cpu_q1_rps, 3),
        "extra": {
            "sf": sf,
            "rows": n_rows,
            "q6_rows_per_sec": round(q6_rps),
            "q6_vs_cpu": round(q6_rps / (n_rows / cpu_q6_dt), 3),
            "ingest_rows_per_sec": round(n_rows / ingest_dt),
            "ingest_gb_per_sec": round(nbytes / ingest_dt / 1e9, 3),
            "hbm_gb_per_sec": round(q1_bytes / q1_dt / 1e9, 1),
            "cpu_q1_rows_per_sec": round(cpu_q1_rps),
            "baseline": "vectorized numpy single-pass (mask+bincount), "
                        "same host",
        },
    }))


if __name__ == "__main__":
    main()
