"""Benchmark: TPC-H Q1/Q6 through the REAL database path, plus the
kernel-plane roofline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "extra"}.

Three tiers, each timed cold (first run after ingest; includes XLA
compile for that shape) and warm (best of N steady-state repeats):

  * kernel — ColumnSource blocks resident in HBM -> compiled SSA program
    (the scan executor with storage bypassed): the HBM roofline.
  * engine — rows ingested through ColumnShard.write/commit into a
    DirBlobStore (portions + WAL on disk), scanned via shard.scan():
    blob IO -> chunk streams -> device blocks -> program. The number
    that corresponds to the reference's ColumnShard scan path
    (ydb/core/tx/columnshard/; ydb_cli/commands/ydb_benchmark.cpp).
  * sql — the same stored shard behind the SQL front door:
    parse -> plan -> ScanExecutor over the portion stream.

Primary metric: engine WARM Q1 rows/s (the database, not the kernel —
VERDICT r3 item 1). vs_baseline divides by the CPU Q1 baseline averaged
over >= 5 runs (a tight vectorized numpy single-pass engine on the same
host; BASELINE.md requires the CPU number be measured, not copied).

Env knobs: YDB_TPU_BENCH_SF (kernel tier, default 10),
YDB_TPU_BENCH_ENGINE_SF (storage tiers, default 1: they stream the
table from disk per run, so duration scales with size but rows/s does
not), YDB_TPU_BENCH_ITERS (default 5), YDB_TPU_BENCH_BLOCK_ROWS
(default 2^21), YDB_TPU_BENCH_BUDGET (seconds, default 1500: storage
tiers are skipped once spent so the JSON line always prints),
YDB_TPU_BENCH_SKIP_ENGINE=1 (kernel-only quick mode),
YDB_TPU_BENCH_PALLAS_COMPARE=1 (force the in-process A/B of the Pallas
one-hot group-by vs the XLA scatter path; default on for TPU backends),
YDB_TPU_BENCH_FUSED_COMPARE=0 (skip the fused-vs-per-agg group-by A/B,
which is on by default on every backend and reports
fused/peragg_q1_rows_per_sec + fused_speedup),
YDB_TPU_BENCH_STATS=0 (skip the column-statistics tier: zone-map
pruning A/B on a selective non-PK filter — stats-on vs the
YDB_TPU_STATS=0 path, bit-identical asserted — reported as
extra.stats_pruning {chunks read/skipped, pruning_hit_rate,
pruning_speedup} plus extra.stats_ndv per-column NDV relative error;
YDB_TPU_BENCH_STATS_ROWS sizes it),
YDB_TPU_BENCH_FUSION=0 (skip the whole-plan fusion tier: warm TPC-H
Q3 executed as ONE fused donated-buffer dispatch — ssa.plan_fuse — vs
the per-node fragment walk at the short-query scale fusion targets,
bit-identity asserted; reported as extra.fusion_* rows/s, speedup and
per-query dispatch counts; YDB_TPU_BENCH_FUSION_SF sizes it,
default 0.001),
YDB_TPU_BENCH_MESH=0 (skip the mesh scale-out tier: Q1/Q6 sharded
scan scaling and Q3 repartition-join throughput through ONE
shard_map'd whole-plan dispatch — parallel.mesh_fuse — vs the
single-chip executor on the same data, bit-identity asserted;
auto-skips under 2 visible devices; YDB_TPU_BENCH_MESH_SF sizes it,
reported as extra.mesh_q{1,6,3}_{rows_per_sec,scaling}).
Engine-tier runs also
report per-stage scan seconds (engine_q{1,6}_stage_seconds:
read/merge/stage/compute) from the streaming reader's StageTimer,
warm-repeat p50/p99 latency from obs.counters histograms
(engine_q{1,6}_latency, sql_q1_latency) and one profiled run's
QueryProfile (engine_q{1,6}_profile, sql_q1_profile: stage seconds,
compile-vs-execute split, pruning counts — obs.profile).
Phase progress logs to stderr; stdout stays the one JSON line.

Robustness: each tier's results checkpoint to disk as the tier
completes (YDB_TPU_BENCH_CHECKPOINT, default BENCH_checkpoint.json;
empty disables) so a wedged tunnel late in a run degrades to
"completed tiers + fresh CPU" instead of losing everything. The CPU
baseline is the MEDIAN of >= 5 runs with the coefficient of variation
reported (cpu_q{1,6}_cv); cv > 0.3 marks the final
``vs_baseline_untrusted`` flag — absolute rates stand, the ratio
doesn't.
"""

import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

_T0 = time.perf_counter()


def _log(stage: str) -> None:
    """Phase progress to stderr (stdout stays the one JSON line)."""
    print(f"[bench +{time.perf_counter() - _T0:7.1f}s] {stage}",
          file=sys.stderr, flush=True)


def probe_backend() -> str | None:
    """Probe the accelerator backend in a SUBPROCESS with a timeout.

    The axon TPU tunnel can wedge such that ``jax.devices()`` hangs
    forever (it ate all of round 4 — BENCH_r04 was rc=1 with zero
    numbers). Probing in a child process bounds the damage: if the child
    does not report a platform within YDB_TPU_BENCH_PROBE_TIMEOUT
    (default 120s), the parent falls back to the CPU backend and reports
    ``extra.tpu_unavailable`` instead of producing nothing. The hung
    child is deliberately ABANDONED, not killed — killing a process
    mid-claim wedges the tunnel for hours (learned the hard way).

    Returns the platform string ("tpu"/"axon"/"cpu") or None when the
    probe hung or crashed.
    """
    timeout = float(os.environ.get("YDB_TPU_BENCH_PROBE_TIMEOUT", "120"))
    code = ("import jax; d = jax.devices(); "
            "print('PLATFORM:' + d[0].platform, flush=True)")
    try:
        child = subprocess.Popen(
            [sys.executable, "-c", code], stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True,
            start_new_session=True)
    except OSError as e:
        _log(f"probe spawn failed: {e}")
        return None
    try:
        out, _ = child.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        _log(f"backend probe hung >{timeout:g}s (tunnel wedged); "
             "abandoning child, falling back to CPU")
        return None
    for line in (out or "").splitlines():
        if line.startswith("PLATFORM:"):
            return line.split(":", 1)[1].strip()
    _log(f"backend probe exited rc={child.returncode} without a platform")
    return None


def _budget_left(budget: float) -> float:
    return budget - (time.perf_counter() - _T0)


_CKPT_TIERS: list = []


def _checkpoint(tier: str, extra: dict) -> None:
    """Persist completed-tier results to disk as each tier finishes
    (atomic tmp+rename). A wedged TPU tunnel at round end then degrades
    to "completed tiers on disk + fresh CPU rerun" instead of losing
    the whole run (VERDICT next-round #1). Path:
    YDB_TPU_BENCH_CHECKPOINT (default BENCH_checkpoint.json; empty/0
    disables). Best-effort: checkpoint IO must never kill the bench."""
    path = os.environ.get("YDB_TPU_BENCH_CHECKPOINT",
                          "BENCH_checkpoint.json")
    if path in ("", "0", "off"):
        return
    _CKPT_TIERS.append(tier)
    # per-tier chaos provenance: a benchmark number is only comparable
    # if no fault scenario was armed while it ran — stamp each tier so
    # a stray YDB_TPU_CHAOS=1 is visible in the artifact
    try:
        from ydb_tpu import chaos

        extra.setdefault("chaos", {})[tier] = (
            "armed" if chaos.armed() else "off")
    except Exception:  # noqa: BLE001 - provenance is best-effort
        pass
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump({"completed_tiers": list(_CKPT_TIERS),
                       "elapsed_s": round(time.perf_counter() - _T0, 1),
                       "extra": extra}, f, indent=2, default=str)
        os.replace(tmp, path)
    except OSError as e:
        _log(f"checkpoint write failed (ignored): {e}")


class _SqlProbeTooSlow(Exception):
    """SQL tier probe exceeded its cap; skip that tier, keep the rest."""


class _BudgetSpent(Exception):
    """Wall-clock budget spent mid-way: skip what remains, keep every
    number already measured (the JSON line must always print, and the
    process must exit before any external timeout kills it — a killed
    TPU claim wedges the tunnel)."""


def cpu_q1(li, cutoff, nls=None):
    """Vectorized single-pass numpy Q1 (the CPU columnar baseline)."""
    m = li["l_shipdate"] <= cutoff
    if nls is None:
        nls = int(li["l_linestatus"].max()) + 1
    rf = li["l_returnflag"][m].astype(np.int64)
    ls = li["l_linestatus"][m].astype(np.int64)
    gid = rf * nls + ls
    ng = int(gid.max()) + 1
    qty = li["l_quantity"][m]
    price = li["l_extendedprice"][m]
    disc = li["l_discount"][m]
    tax = li["l_tax"][m]
    disc_price = price * (100 - disc)          # scale 4
    charge = disc_price * (100 + tax)          # scale 6
    out = {
        "count": np.bincount(gid, minlength=ng),
    }
    for name, col in (("sum_qty", qty), ("sum_base_price", price),
                      ("sum_disc_price", disc_price),
                      ("sum_charge", charge), ("sum_disc", disc)):
        out[name] = np.bincount(gid, weights=col.astype(np.float64),
                                minlength=ng)
    keep = out["count"] > 0
    out = {k: v[keep] for k, v in out.items()}
    out["gid"] = np.flatnonzero(keep)
    return out, int(m.sum()), nls


def cpu_q6(li, d0, d1):
    m = ((li["l_shipdate"] >= d0) & (li["l_shipdate"] < d1)
         & (li["l_discount"] >= 5) & (li["l_discount"] <= 7)
         & (li["l_quantity"] < 2400))
    return int(np.sum(li["l_extendedprice"][m] * li["l_discount"][m]))


def check_q1(out1, li, nls, base1):
    res1 = out1.to_numpy() if hasattr(out1, "to_numpy") else out1
    n1 = int(out1.length) if hasattr(out1, "length") else len(
        res1["count_order"])
    eng_gid = (np.asarray(res1["l_returnflag"][:n1]).astype(np.int64) * nls
               + np.asarray(res1["l_linestatus"][:n1]).astype(np.int64))
    eng_order = np.argsort(eng_gid)
    assert np.array_equal(eng_gid[eng_order], base1["gid"]), (
        "engine/baseline group keys differ")
    for eng_col, base_col in (("count_order", "count"),
                              ("sum_qty", "sum_qty"),
                              ("sum_base_price", "sum_base_price"),
                              ("sum_disc_price", "sum_disc_price"),
                              ("sum_charge", "sum_charge")):
        ev = np.asarray(res1[eng_col][:n1], dtype=np.float64)[eng_order]
        assert np.allclose(ev, base1[base_col], rtol=1e-9), (
            f"engine/baseline mismatch on {eng_col}")


def timed_cold_warm(fn, iters, deadline=None, hist=None):
    """(cold_seconds, warm_best_seconds, last_result).

    ``deadline`` (seconds since bench start) bounds the WARM repeats:
    the budget must hold mid-tier, not just between tiers — an overrun
    here is what gets the whole bench killed externally (and a killed
    TPU claim wedges the tunnel for hours). With no warm repeat left,
    warm reports the cold time. ``hist`` (obs.counters.Histogram)
    observes every WARM repeat — per-tier p50/p99 in the report."""
    t0 = time.perf_counter()
    out = fn()
    cold = time.perf_counter() - t0
    warm = float("inf")
    for _ in range(iters):
        if deadline is not None and \
                time.perf_counter() - _T0 > deadline:
            break
        t0 = time.perf_counter()
        out = fn()
        dt = time.perf_counter() - t0
        if hist is not None:
            hist.observe(dt)
        warm = min(warm, dt)
    return cold, (cold if warm == float("inf") else warm), out


def _latency_summary(hist) -> dict | None:
    """p50/p99 (ms) off a per-tier histogram; None when it saw < 2
    repeats (a single sample's percentiles are just that sample)."""
    if hist.count < 2:
        return None
    return {"p50_ms": round(hist.percentile(0.5) * 1e3, 3),
            "p99_ms": round(hist.percentile(0.99) * 1e3, 3),
            "samples": hist.count}


def _profiled_with_movement(label, fn, extra, key, query_class):
    """One profiled run with the data-movement timeline forced on:
    embeds the run's stage-occupancy fractions/overlaps plus the
    movement byte DELTAS (blob read / decoded / staged / resident /
    shuffle) as rates in ``extra[key + "_occupancy"/"_movement"]``.
    Returns the profile handle (ph.profile carries the full dict)."""
    from ydb_tpu.obs import profile as profile_mod
    from ydb_tpu.obs import timeline

    before = timeline.movement_snapshot()
    prev = timeline.TIMELINE_FORCE
    timeline.TIMELINE_FORCE = True
    try:
        with profile_mod.profiled(label, query_class=query_class) as ph:
            fn()
    finally:
        timeline.TIMELINE_FORCE = prev
    after = timeline.movement_snapshot()
    secs = getattr(ph.profile, "seconds", 0.0) or 0.0
    mv = {}
    for k, v in sorted(after.items()):
        d = v - before.get(k, 0)
        if d:
            mv[k] = d
            if secs:
                mv[k + "_per_sec"] = round(d / secs)
    if mv:
        extra[key + "_movement"] = mv
    occ = getattr(ph.profile, "stage_occupancy", None)
    if occ:
        extra[key + "_occupancy"] = occ
    return ph


def _q1_flag_ab(src, blocks, n_rows, block_rows, iters, sides, set_flag):
    """In-process q1 A/B over a trace-time force flag: fresh executors
    per side — the flag is consulted at trace time, and separate
    function objects trace separately. (No subprocesses: a child python
    would try to claim the TPU the parent already holds and hang on the
    tunnel.) ``sides`` maps label -> forced flag value; ``set_flag``
    applies it (None restores the default)."""
    import jax

    from ydb_tpu.engine.scan import ScanExecutor
    from ydb_tpu.workload import tpch

    out = {}
    for label, force in sides:
        set_flag(force)
        try:
            ex = ScanExecutor(tpch.q1_program(), src,
                              block_rows=block_rows)

            def go():
                r = ex.finalize([ex.run_block(b) for b in blocks])
                jax.block_until_ready(r)
                return r

            _, warm, _ = timed_cold_warm(go, iters)
            out[f"{label}_q1_rows_per_sec"] = round(n_rows / warm)
        except Exception as e:  # noqa: BLE001 - report, don't die
            out[f"{label}_error"] = repr(e)[-300:]
        finally:
            set_flag(None)
    return out


def fused_ab(src, blocks, n_rows, block_rows, iters):
    """Fused single-contraction group-by vs the per-aggregate reduction
    path (PR 3 acceptance: fused kernel-tier Q1 warm >= 2x per-agg on
    CPU)."""
    from ydb_tpu.ssa import kernels

    def set_flag(v):
        kernels.FUSED_FORCE = v

    out = _q1_flag_ab(src, blocks, n_rows, block_rows, iters,
                      (("fused", True), ("peragg", False)), set_flag)
    if "fused_q1_rows_per_sec" in out and "peragg_q1_rows_per_sec" in out:
        out["fused_speedup"] = round(
            out["fused_q1_rows_per_sec"]
            / max(out["peragg_q1_rows_per_sec"], 1), 2)
    return out


def pallas_ab(src, blocks, n_rows, block_rows, iters):
    """Pallas one-hot group-by forced ON vs OFF (the XLA scatter path)."""
    from ydb_tpu.ssa import pallas_kernels

    def set_flag(v):
        pallas_kernels.FORCE = v

    return _q1_flag_ab(src, blocks, n_rows, block_rows, iters,
                       (("pallas", True), ("scatter", False)), set_flag)


def run_stats_ab(extra: dict, iters: int) -> None:
    """Column-statistics tier: zone-map scan pruning A/B (stats on vs
    the YDB_TPU_STATS=0 path) on a selective non-PK filter over a
    time-correlated table, plus aggregator NDV accuracy. Results are
    asserted bit-identical between the two sides; reported extras:
    pruning hit rate (chunks skipped / total), selective-scan speedup
    and per-column NDV relative error."""
    import numpy as np  # noqa: F811 - local alias for the helper

    from ydb_tpu.obs.kernelbench import bench_pruning, \
        build_pruning_shard
    from ydb_tpu.stats.aggregator import StatisticsAggregator

    rows = int(os.environ.get("YDB_TPU_BENCH_STATS_ROWS", str(1 << 20)))
    shard, n = build_pruning_shard(rows, 1 << 14)
    report = bench_pruning(rows, chunk_rows=1 << 14,
                           iters=max(2, iters // 2), shard=(shard, n))
    total = report["nostats_chunks_read"]
    hit = 1.0 - report["stats_chunks_read"] / max(total, 1)
    report["pruning_hit_rate"] = round(hit, 3)
    extra["stats_pruning"] = report
    # NDV accuracy on the SAME shard through the aggregator (no second
    # build/serialize pass)
    agg = StatisticsAggregator()
    merged = agg.collect_shard(shard)
    ndv = {}
    from ydb_tpu.engine.portion import read_portion_blob

    cols: dict = {}
    for m in shard.visible_portions():
        c, v = read_portion_blob(shard.store, m.blob_id)
        for k, arr in c.items():
            ok = v.get(k)
            cols.setdefault(k, []).append(
                arr if ok is None else arr[ok])
    for k, parts in cols.items():
        true = len(np.unique(np.concatenate(parts)))
        est = merged[k].ndv
        ndv[k] = {"true": true, "est": est,
                  "rel_err": round(abs(est - true) / max(true, 1), 4)}
    extra["stats_ndv"] = ndv
    _log(f"stats tier: hit_rate={report['pruning_hit_rate']} "
         f"speedup=x{report.get('pruning_speedup')} "
         f"chunks_skipped={report['chunks_skipped']}")


def run_fusion_ab(extra: dict, iters: int) -> None:
    """Whole-plan fusion tier: warm TPC-H Q3 (joins + grouped top-k)
    executed as ONE fused donated-buffer dispatch (ssa.plan_fuse) vs
    the per-node fragment walk, same Database both sides, bit-identity
    asserted inside the bench. Runs at the short-query scale fusion
    targets (PR 9 acceptance: fused warm >= 1.5x per-fragment on CPU
    with a single dispatch per shape class)."""
    from ydb_tpu.obs.kernelbench import bench_fusion

    sf = float(os.environ.get("YDB_TPU_BENCH_FUSION_SF", "0.001"))
    r = bench_fusion(sf, max(3, iters))
    for k in ("rows", "fused_rows_per_sec", "walk_rows_per_sec",
              "fused_speedup", "fused_dispatches",
              "fragment_dispatches", "fragments_elided", "identical"):
        extra[f"fusion_{k}"] = r[k]
    _log(f"fusion tier: x{r['fused_speedup']} fused over walk "
         f"({r['fused_dispatches']} dispatch vs "
         f"{r['fragment_dispatches']} fragments, "
         f"identical={r['identical']})")


def run_mesh_tier(extra: dict, iters: int) -> None:
    """Mesh scale-out tier: whole-plan SPMD execution over the device
    mesh (parallel.mesh_fuse — one sharded donated-buffer dispatch with
    all_to_all repartition for the joins) vs the single-chip executor on
    the SAME data. Q1/Q6 measure sharded scan+aggregate scaling, Q3 the
    repartition-join throughput; every mesh result is asserted
    bit-identical to the single-chip side. Skips (recorded) when fewer
    than 2 devices are visible; YDB_TPU_BENCH_MESH_SF sizes it."""
    import jax

    n_dev = len(jax.devices())
    if n_dev < 2:
        extra["mesh_tier_skipped"] = f"needs >=2 devices, have {n_dev}"
        return

    from ydb_tpu.engine.scan import ColumnSource
    from ydb_tpu.parallel.mesh import make_mesh
    from ydb_tpu.parallel.mesh_exec import MeshDatabase, MeshPlanExecutor
    from ydb_tpu.plan import (
        Database, TableScan, Transform, execute_plan, to_host,
    )
    from ydb_tpu.workload import tpch

    sf = float(os.environ.get("YDB_TPU_BENCH_MESH_SF", "0.05"))
    data = tpch.TpchData(sf=sf, seed=29)
    single_db = Database(
        sources={t: ColumnSource(cols, data.schema(t), data.dicts)
                 for t, cols in data.tables.items()},
        dicts=data.dicts)
    mesh_db = MeshDatabase(
        sources={
            t: [ColumnSource({k: v[s::n_dev] for k, v in cols.items()},
                             data.schema(t), data.dicts)
                for s in range(n_dev)]
            for t, cols in data.tables.items()
        },
        dicts=data.dicts)
    mex = MeshPlanExecutor(mesh_db, make_mesh(n_dev))
    n_rows = len(data.tables["lineitem"]["l_orderkey"])
    extra["mesh_devices"] = n_dev
    extra["mesh_sf"] = sf
    extra["mesh_rows"] = n_rows

    plans = {
        "q1": Transform(TableScan("lineitem"), tpch.q1_program()),
        "q6": Transform(TableScan("lineitem"), tpch.q6_program()),
        "q3": tpch.q3_plan(),
    }
    for name, plan in plans.items():
        def run_mesh(plan=plan):
            out = mex.execute_fused(plan)
            assert out is not None, f"mesh path declined {name}"
            return out

        def run_single(plan=plan):
            return to_host(execute_plan(plan, single_db, use_dq=False))

        _, mwarm, mres = timed_cold_warm(run_mesh, iters)
        _, swarm, sres = timed_cold_warm(run_single, iters)
        assert mres.num_rows == sres.num_rows, name
        for col in mres.cols:
            np.testing.assert_array_equal(
                np.asarray(mres.cols[col][0]),
                np.asarray(sres.cols[col][0]),
                err_msg=f"mesh/single mismatch: {name}.{col}")
        extra[f"mesh_{name}_rows_per_sec"] = round(n_rows / mwarm)
        extra[f"single_{name}_rows_per_sec"] = round(n_rows / swarm)
        # > 1 means the sharded dispatch beats one chip end-to-end at
        # this scale; the per-device row count is what actually shrinks
        extra[f"mesh_{name}_scaling"] = round(swarm / mwarm, 2)
        extra[f"mesh_{name}_identical"] = True
    _log(f"mesh tier: {n_dev} devices, q1 x"
         f"{extra['mesh_q1_scaling']} q6 x{extra['mesh_q6_scaling']} "
         f"q3 x{extra['mesh_q3_scaling']} vs single chip")


def _hist_delta_p(hist, base_buckets, q):
    """Percentile over the samples a histogram gained SINCE
    ``base_buckets`` (a list(hist.buckets) snapshot taken while the
    cluster was quiet) — per-phase p50/p99 off the cumulative
    query_latency_seconds histograms, same within-bucket interpolation
    as Histogram.percentile. None under 2 new samples."""
    buckets = [n - b for n, b in zip(hist.buckets, base_buckets)]
    count = sum(buckets)
    if count < 2:
        return None
    target = q * count
    acc = 0
    for i, n in enumerate(buckets):
        if not n:
            continue
        acc += n
        if acc >= target:
            if i >= len(hist.bounds):
                return hist.bounds[-1]
            lo = hist.bounds[i - 1] if i else 0.0
            hi = hist.bounds[i]
            return lo + (hi - lo) * (target - (acc - n)) / n
    return hist.bounds[-1]


def _syncsan_warm(label: str, fn, extra: dict, key: str) -> None:
    """One warm statement under the sync sanitizer
    (analysis/syncsan): record the host-boundary counters the
    statement actually crossed — H2D/D2H transfers, blocking syncs,
    XLA compiles — in the bench JSON. The dispatch-purity scoreboard
    (ROADMAP item 1): warm compiles must be 0, syncs bounded."""
    from ydb_tpu.analysis import syncsan

    with syncsan.activate():
        st = syncsan.begin_statement(label)
        fn()
        snap = syncsan.end_statement(st)
    if snap is not None:
        extra[key] = snap


def _memsan_warm(label: str, fn, extra: dict, key: str) -> None:
    """One warm statement under the memory sanitizer
    (analysis/memsan): record the device-byte ledger the statement
    actually accumulated — peak/live HBM bytes, charge count, and the
    unbudgeted-allocation count that must stay 0 (devmem M001's
    runtime shadow). The HBM-footprint scoreboard (ROADMAP item 1):
    warm peak bytes per statement, expected 0 on the cached engine
    path."""
    from ydb_tpu.analysis import memsan

    with memsan.activate():
        st = memsan.begin_statement(label)
        fn()
        snap = memsan.end_statement(st, enforce=False)
    if snap is not None:
        snap.pop("by_component", None)
        extra[key] = snap


def run_serving_tier(extra: dict, budget: float) -> None:
    """Serving-throughput tier: N concurrent sessions firing a TPC-H
    Q1/Q6 statement mix at one cluster, batching off vs on
    (kqp/batch.py micro-batched fused dispatch + shared scans), QPS
    from the timed burst and p50/p99 from the PR 6
    ``query_latency_seconds`` histograms (per-phase bucket deltas).
    The acceptance bar rides the 100-session level: batching on must
    hold >= 2x the QPS of batching off on the warm Q1-heavy mix.
    YDB_TPU_BENCH_SERVING_SF / _SESSIONS / _WINDOW_MS size it."""
    import threading

    from ydb_tpu.kqp.session import Cluster
    from ydb_tpu.scheme.model import type_to_str
    from ydb_tpu.workload import tpch
    from ydb_tpu.workload.queries import TPCH

    sf = float(os.environ.get("YDB_TPU_BENCH_SERVING_SF", "0.01"))
    levels = [int(x) for x in os.environ.get(
        "YDB_TPU_BENCH_SERVING_SESSIONS", "10,100,1000").split(",")
        if x.strip()]
    window_ms = float(os.environ.get(
        "YDB_TPU_BENCH_SERVING_WINDOW_MS", "25"))
    data = tpch.TpchData(sf=sf, seed=29)
    extra["serving_sf"] = sf
    extra["serving_rows"] = len(data.tables["lineitem"]["l_orderkey"])
    extra["serving_window_ms"] = window_ms
    statements = (TPCH["q1"], TPCH["q6"])

    def boot():
        c = Cluster()
        s = c.session()
        schema = data.schema("lineitem")
        cols = ", ".join(f"{f.name} {type_to_str(f.type)}"
                         for f in schema.fields)
        s.execute(f"CREATE TABLE lineitem ({cols}, "
                  f"PRIMARY KEY (l_orderkey)) WITH (shards = 1)")
        src = data.tables["lineitem"]
        arrays = {}
        for f in schema.fields:
            v = src[f.name]
            if f.type.is_string:
                arrays[f.name] = [
                    bytes(x) for x in data.dicts[f.name].decode(
                        np.asarray(v, dtype=np.int32))]
            else:
                arrays[f.name] = v
        c.tables["lineitem"].insert(arrays)
        c._invalidate_plans()
        for sql in statements:  # warm plan + compile caches
            s.execute(sql)
        return c

    def burst(c, concurrency, per_session):
        sessions = [c.session() for _ in range(concurrency)]
        errs: list = []
        gate = threading.Barrier(concurrency + 1)

        def worker(s, i):
            try:
                gate.wait()
                for j in range(per_session):
                    s.execute(statements[(i + j) % len(statements)])
            except Exception as e:  # noqa: BLE001 - recorded evidence
                errs.append(repr(e)[-200:])

        threads = [threading.Thread(target=worker, args=(s, i))
                   for i, s in enumerate(sessions)]
        for t in threads:
            t.start()
        gate.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        return time.perf_counter() - t0, errs

    sides = {}
    for side in ("off", "on"):
        _log(f"serving tier: boot (batching {side})")
        sides[side] = boot()
        if side == "on":
            sides[side].batcher.window_ms = window_ms
    try:
        hists = {
            side: c.counters.group(
                query_class="select_agg").histogram(
                    "query_latency_seconds")
            for side, c in sides.items()}
        for n in levels:
            if _budget_left(budget) < (30 if n <= 100 else 120):
                extra[f"serving_{n}_skipped"] = "budget"
                continue
            per_session = max(1, 200 // n)
            total = n * per_session
            for side, c in sides.items():
                if side == "on":
                    # the window closes early once every admitted
                    # session of the level has joined the group
                    c.batcher.max_batch = max(2, n)
                base = list(hists[side].buckets)
                wall, errs = burst(c, n, per_session)
                if errs:
                    extra[f"serving_{n}_{side}_errors"] = errs[:3]
                extra[f"serving_{n}_qps_{side}"] = round(total / wall, 1)
                for q, tag in ((0.5, "p50"), (0.99, "p99")):
                    v = _hist_delta_p(hists[side], base, q)
                    if v is not None:
                        extra[f"serving_{n}_{tag}_ms_{side}"] = round(
                            v * 1e3, 3)
            off = extra.get(f"serving_{n}_qps_off")
            on = extra.get(f"serving_{n}_qps_on")
            if off and on:
                extra[f"serving_{n}_qps_speedup"] = round(on / off, 2)
                _log(f"serving tier: {n} sessions "
                     f"{off} -> {on} qps "
                     f"(x{extra[f'serving_{n}_qps_speedup']})")
        snap = sides["on"].batcher.snapshot()
        for k in ("batches", "batched_statements", "dedup_dispatches",
                  "stacked_dispatches", "max_batch_size",
                  "scan_staged", "scan_attached"):
            extra[f"serving_batch_{k}"] = snap[k]
        # warm per-statement host-boundary counters through the full
        # session path (syncsan windows open in _execute_admitted, the
        # counters ride the statement's profile): the serving-tier
        # dispatch-purity evidence next to the QPS numbers
        if _budget_left(budget) > 20:
            from ydb_tpu.analysis import syncsan

            with syncsan.activate():
                s = sides["off"].session()
                for name, sql in (("q1", TPCH["q1"]),
                                  ("q6", TPCH["q6"])):
                    s.execute(sql)
                    p = s.last_profile
                    if p is not None and p.syncsan:
                        extra[f"serving_{name}_syncsan"] = p.syncsan
        # warm per-statement device-byte ledger through the same full
        # session path (memsan windows ride the statement bounds): the
        # serving-tier HBM-footprint evidence next to the QPS numbers
        if _budget_left(budget) > 20:
            from ydb_tpu.analysis import memsan

            with memsan.activate():
                s = sides["off"].session()
                for name, sql in (("q1", TPCH["q1"]),
                                  ("q6", TPCH["q6"])):
                    s.execute(sql)
                    p = s.last_profile
                    if p is not None and p.memsan:
                        extra[f"serving_{name}_memsan"] = p.memsan
    finally:
        for c in sides.values():
            c.stop()


def run_net_tier(extra: dict, budget: float) -> None:
    """Network serving tier: N loopback pgwire CLIENTS (real sockets,
    real protocol framing, one connection each) firing a mixed TPC-H
    Q1/Q6 + point-INSERT ingest workload at one cluster behind the
    multi-tenant front door (ydb_tpu/serving/), batching off vs on.
    Clients alternate between two weighted tenants ("gold" w=3,
    "bronze" w=1) via the `tenant` startup parameter, so the numbers
    exercise tenant resolution, per-pool admission, and the
    cross-connection batch grouping that PR 17 unlocked (reads run
    outside the pgwire server lock). Latency is measured CLIENT-side
    (send-Query to ReadyForQuery) and reported per tenant as p50/p99.
    YDB_TPU_BENCH_NET_SF / _CLIENTS / _WINDOW_MS size it."""
    import socket
    import struct
    import threading

    from ydb_tpu import serving
    from ydb_tpu.api.pgwire import PgWireServer
    from ydb_tpu.kqp.session import Cluster
    from ydb_tpu.scheme.model import type_to_str
    from ydb_tpu.workload import tpch
    from ydb_tpu.workload.queries import TPCH

    sf = float(os.environ.get("YDB_TPU_BENCH_NET_SF", "0.01"))
    levels = [int(x) for x in os.environ.get(
        "YDB_TPU_BENCH_NET_CLIENTS", "100,1000").split(",")
        if x.strip()]
    window_ms = float(os.environ.get(
        "YDB_TPU_BENCH_NET_WINDOW_MS", "25"))
    data = tpch.TpchData(sf=sf, seed=29)
    extra["net_sf"] = sf
    extra["net_window_ms"] = window_ms
    statements = (TPCH["q1"], TPCH["q6"])
    tenants = ("gold", "bronze")

    class _Wire:
        """Minimal pg frontend: startup (with tenant param) + simple
        query, independent of the server code like tests' MiniPgClient
        but trimmed to what the bench times."""

        def __init__(self, port, tenant):
            for attempt in range(5):  # connect storms vs listen backlog
                try:
                    self.sock = socket.create_connection(
                        ("127.0.0.1", port), timeout=120)
                    break
                except OSError:
                    if attempt == 4:
                        raise
                    time.sleep(0.05 * (attempt + 1))
            params = (b"user\x00bench\x00database\x00postgres\x00"
                      b"tenant\x00" + tenant.encode() + b"\x00\x00")
            self.sock.sendall(
                struct.pack("!II", len(params) + 8, 196608) + params)
            while self._msg()[0] != b"Z":
                pass

        def _recv(self, n):
            buf = b""
            while len(buf) < n:
                c = self.sock.recv(n - len(buf))
                if not c:
                    raise ConnectionError("server closed")
                buf += c
            return buf

        def _msg(self):
            t = self._recv(1)
            (ln,) = struct.unpack("!I", self._recv(4))
            return t, self._recv(ln - 4)

        def query(self, sql):
            q = sql.encode() + b"\x00"
            self.sock.sendall(
                b"Q" + struct.pack("!I", len(q) + 4) + q)
            err = None
            while True:
                t, body = self._msg()
                if t == b"E":
                    err = body
                elif t == b"Z":
                    return err

        def close(self):
            try:
                self.sock.sendall(b"X" + struct.pack("!I", 4))
            finally:
                self.sock.close()

    def boot():
        c = Cluster()
        # the front door's per-pool caps are the shed boundary here;
        # keep the legacy global valve out of the way of the burst
        c.max_inflight_statements = max(
            c.max_inflight_statements, 1 << 14)
        reg = serving.TenantRegistry()
        reg.register("gold", weight=3.0, max_inflight=32,
                     queue_size=4096)
        reg.register("bronze", weight=1.0, max_inflight=16,
                     queue_size=4096)
        serving.install(c, reg)
        s = c.session()
        schema = data.schema("lineitem")
        cols = ", ".join(f"{f.name} {type_to_str(f.type)}"
                         for f in schema.fields)
        s.execute(f"CREATE TABLE lineitem ({cols}, "
                  f"PRIMARY KEY (l_orderkey)) WITH (shards = 1)")
        src = data.tables["lineitem"]
        arrays = {}
        for f in schema.fields:
            v = src[f.name]
            if f.type.is_string:
                arrays[f.name] = [
                    bytes(x) for x in data.dicts[f.name].decode(
                        np.asarray(v, dtype=np.int32))]
            else:
                arrays[f.name] = v
        c.tables["lineitem"].insert(arrays)
        s.execute("CREATE TABLE net_ingest (k int64, v int64, "
                  "PRIMARY KEY (k))")
        c._invalidate_plans()
        for sql in statements:  # warm plan + compile caches
            s.execute(sql)
        return c, PgWireServer(c).start()

    def burst(port, n, per_client):
        lat = {t: [] for t in tenants}
        errs: list = []
        rec = threading.Lock()
        gate = threading.Barrier(n + 1)

        def worker(i):
            tenant = tenants[i % len(tenants)]
            cl, mine = None, []
            try:
                cl = _Wire(port, tenant)
            except Exception as e:  # noqa: BLE001 - recorded evidence
                with rec:
                    errs.append("connect: " + repr(e)[-160:])
            try:
                gate.wait()
                if cl is None:
                    return
                for j in range(per_client):
                    if i % 4 == 3:  # ingest rider on every 4th client
                        sql = (f"INSERT INTO net_ingest VALUES "
                               f"({i * 1000000 + j}, {j})")
                    else:
                        sql = statements[(i + j) % len(statements)]
                    t0 = time.perf_counter()
                    err = cl.query(sql)
                    mine.append(time.perf_counter() - t0)
                    if err is not None:
                        with rec:
                            errs.append(err[:160].decode("latin-1"))
            except Exception as e:  # noqa: BLE001 - recorded evidence
                with rec:
                    errs.append(repr(e)[-160:])
            finally:
                if cl is not None:
                    try:
                        cl.close()
                    except Exception:  # noqa: BLE001 - teardown
                        pass
                with rec:
                    lat[tenant].extend(mine)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        gate.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        return time.perf_counter() - t0, lat, errs

    def _pct(xs, q):
        if not xs:
            return None
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(q * len(xs)))]

    sides = {}
    for side in ("off", "on"):
        _log(f"net tier: boot (batching {side})")
        sides[side] = boot()
        if side == "on":
            sides[side][0].batcher.window_ms = window_ms
    try:
        for n in levels:
            if _budget_left(budget) < (60 if n <= 100 else 240):
                extra[f"net_{n}_skipped"] = "budget"
                continue
            per_client = max(1, 400 // n)
            for side, (c, srv) in sides.items():
                if side == "on":
                    c.batcher.max_batch = max(2, n)
                wall, lat, errs = burst(srv.port, n, per_client)
                done = sum(len(v) for v in lat.values())
                if errs:
                    extra[f"net_{n}_{side}_errors"] = len(errs)
                    extra[f"net_{n}_{side}_error_sample"] = errs[:3]
                extra[f"net_{n}_qps_{side}"] = round(done / wall, 1)
                for tname, xs in lat.items():
                    for q, tag in ((0.5, "p50"), (0.99, "p99")):
                        v = _pct(xs, q)
                        if v is not None:
                            extra[f"net_{n}_{tname}_{tag}_ms_{side}"] \
                                = round(v * 1e3, 3)
            off = extra.get(f"net_{n}_qps_off")
            on = extra.get(f"net_{n}_qps_on")
            if off and on:
                extra[f"net_{n}_qps_speedup"] = round(on / off, 2)
                _log(f"net tier: {n} clients {off} -> {on} qps "
                     f"(x{extra[f'net_{n}_qps_speedup']})")
        snap = sides["on"][0].batcher.snapshot()
        for k in ("batches", "batched_statements", "dedup_dispatches",
                  "max_batch_size"):
            extra[f"net_batch_{k}"] = snap[k]
        door = sides["on"][0].front_door.snapshot()
        for tname, st in door.items():
            extra[f"net_pool_{tname}_admitted"] = st["admitted"]
            extra[f"net_pool_{tname}_shed"] = st["shed"]
    finally:
        for c, srv in sides.values():
            srv.stop()
            c.stop()


def run_ooc(extra: dict, iters: int, block_rows: int) -> None:
    """Out-of-core engine-tier run at a LARGE scale factor (SURVEY
    §7.2 item 7): lineitem generates in bounded chunks (the full table
    never exists in memory), ingests through ColumnShard.write/commit
    onto disk, and Q1/Q6 scan through the streaming reader. The Q1/Q6
    baselines accumulate incrementally per generated chunk, so
    verification is out-of-core too. Records SF, ingest/scan rows/s,
    on-disk bytes, and peak RSS against an explicit budget
    (YDB_TPU_BENCH_OOC_RSS_GB, default 24)."""
    import resource

    import jax

    from ydb_tpu.blocks.dictionary import DictionarySet
    from ydb_tpu.engine.blobs import DirBlobStore
    from ydb_tpu.engine.shard import ColumnShard, ShardConfig
    from ydb_tpu.workload import tpch

    ooc_sf = float(os.environ.get("YDB_TPU_BENCH_OOC_SF", "0"))
    if not ooc_sf:
        return
    budget_gb = float(os.environ.get("YDB_TPU_BENCH_OOC_RSS_GB", "24"))
    root = os.environ.get("YDB_TPU_BENCH_OOC_DIR")
    _log(f"ooc tier: sf={ooc_sf:g} rss budget {budget_gb:g} GB")
    cutoff = tpch._days("1998-12-01") - 90
    d0, d1 = tpch._days("1994-01-01"), tpch._days("1995-01-01")
    ooc: dict = {"sf": ooc_sf, "rss_budget_gb": budget_gb}
    extra["ooc"] = ooc
    with tempfile.TemporaryDirectory(
            prefix="ydbtpu_ooc_", dir=root) as tmp:
        dicts = DictionarySet()
        # streaming slabs stay modest on the OOC tier: double-buffered
        # H2D works at block granularity, so giant in-memory-tier
        # blocks (1<<21 default) would leave compute waiting on one
        # huge transfer instead of overlapping many small ones
        ooc_block_rows = min(block_rows, 1 << 18)
        shard = ColumnShard(
            "ooc", tpch.LINEITEM_SCHEMA, DirBlobStore(tmp),
            dicts=dicts,
            config=ShardConfig(compact_portion_threshold=10 ** 9,
                               scan_block_rows=ooc_block_rows,
                               portion_chunk_rows=1 << 18))
        # incremental Q1/Q6 baselines: accumulated per chunk, O(1) state
        q1_acc: dict[str, np.ndarray] = {}
        q6_rev = 0
        rows = 0
        t0 = time.perf_counter()
        for chunk in tpch.lineitem_chunks(ooc_sf, dicts):
            wid = shard.write(chunk)
            shard.commit([wid])
            rows += len(chunk["l_orderkey"])
            # nls is structurally 2 (the linestatus dictionary holds
            # exactly O and F): per-chunk inference would mis-bin a
            # chunk whose rows land on one side of the cutoff
            base1, _n, nls = cpu_q1(chunk, cutoff, nls=2)
            for k in ("count", "sum_qty", "sum_base_price",
                      "sum_disc_price", "sum_charge"):
                tgt = q1_acc.setdefault(k, np.zeros(16))
                tgt[base1["gid"]] += base1[k]
            q6_rev += cpu_q6(chunk, d0, d1)
        ingest_s = time.perf_counter() - t0
        ooc["rows"] = rows
        ooc["ingest_rows_per_sec"] = round(rows / ingest_s)
        stored = sum(shard.store.size(m.blob_id)
                     for m in shard.visible_portions())
        ooc["stored_gb"] = round(stored / 1e9, 2)
        _log(f"ooc tier: {rows} rows, {ooc['stored_gb']} GB on disk; "
             "scans")

        def run(prog):
            def go():
                return shard.scan(prog)
            return go

        c1, w1, out1 = timed_cold_warm(run(tpch.q1_program()),
                                       max(1, iters // 2))
        if shard.last_scan_pipeline:
            ooc["pipeline"] = shard.last_scan_pipeline
        c6, w6, out6 = timed_cold_warm(run(tpch.q6_program()),
                                       max(1, iters // 2))
        # verify against the incrementally-accumulated baselines
        res = {n: np.asarray(v[0]) for n, v in out1.cols.items()}
        gid = (res["l_returnflag"].astype(np.int64) * nls
               + res["l_linestatus"].astype(np.int64))
        order = np.argsort(gid)
        live = np.flatnonzero(q1_acc["count"] > 0)
        assert np.array_equal(gid[order], live), "ooc q1 keys"
        assert np.allclose(
            res["sum_charge"].astype(np.float64)[order],
            q1_acc["sum_charge"][live], rtol=1e-9), "ooc q1 charge"
        assert int(np.asarray(out6.cols["revenue"][0])[0]) == q6_rev
        ooc["q1_cold_rows_per_sec"] = round(rows / c1)
        ooc["q1_warm_rows_per_sec"] = round(rows / w1)
        ooc["q6_warm_rows_per_sec"] = round(rows / w6)
        # streaming-pipeline A/B (same scan, morsel pipeline OFF):
        # verified bit-identical against the pipelined result, speedup
        # recorded; then ONE profiled pipelined run embeds the stage
        # occupancy (incl. the movement|compute overlap coefficient)
        # and the movement byte rates — the OOC overlap acceptance gate
        from ydb_tpu.engine import stream_sched

        prev_force = stream_sched.PIPELINE_FORCE
        stream_sched.PIPELINE_FORCE = False
        try:
            _cs, ws, outs = timed_cold_warm(run(tpch.q1_program()),
                                            max(1, iters // 2))
            _cs6, ws6, outs6 = timed_cold_warm(run(tpch.q6_program()),
                                               max(1, iters // 2))
        finally:
            stream_sched.PIPELINE_FORCE = prev_force
        for pipe, ser, q in ((out1, outs, "q1"), (out6, outs6, "q6")):
            for n, v in ser.cols.items():
                a = np.asarray(v[0])
                b = np.asarray(pipe.cols[n][0])
                assert (a.dtype == b.dtype and a.shape == b.shape
                        and a.tobytes() == b.tobytes()), \
                    f"ooc {q} serialized/pipelined mismatch on {n}"
        ooc["q1_serialized_rows_per_sec"] = round(rows / ws)
        ooc["pipeline_speedup_q1"] = round(ws / w1, 2)
        ooc["q6_serialized_rows_per_sec"] = round(rows / ws6)
        ooc["pipeline_speedup_q6"] = round(ws6 / w6, 2)
        _profiled_with_movement("ooc_q1_pipelined",
                                run(tpch.q1_program()), ooc, "q1",
                                query_class="ooc")
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
        ooc["peak_rss_gb"] = round(peak, 2)
        ooc["within_budget"] = peak <= budget_gb
        ooc["backend"] = jax.default_backend()
    _log(f"ooc tier done: peak rss {ooc['peak_rss_gb']} GB")


def main():
    sf = float(os.environ.get("YDB_TPU_BENCH_SF", "10"))
    engine_sf = float(os.environ.get("YDB_TPU_BENCH_ENGINE_SF", "1"))
    iters = int(os.environ.get("YDB_TPU_BENCH_ITERS", "5"))
    block_rows = int(os.environ.get("YDB_TPU_BENCH_BLOCK_ROWS",
                                    str(1 << 21)))
    # wall-clock budget: storage tiers are skipped (fail-soft, kernel
    # numbers still report) once the budget is spent — the driver's
    # bench run must always produce its one JSON line
    budget = float(os.environ.get("YDB_TPU_BENCH_BUDGET", "1500"))

    # un-wedgeable backend selection (VERDICT r4 weak #1): probe the
    # accelerator in a subprocess; on hang/crash, pin the CPU backend
    # BEFORE any jax backend initialization in this process
    tpu_unavailable = False
    if os.environ.get("YDB_TPU_BENCH_FORCE_CPU", "0") not in (
            "0", "", "off"):
        platform = "cpu(forced)"
    else:
        platform = probe_backend()
    if platform is None:
        tpu_unavailable = True
    _log(f"backend probe: {platform!r}")

    import jax

    if tpu_unavailable or platform in ("cpu", "cpu(forced)"):
        # sitecustomize ignores JAX_PLATFORMS env; only the config
        # update after import works in this environment
        jax.config.update("jax_platforms", "cpu")
        if "YDB_TPU_BENCH_SF" not in os.environ:
            # the default SF is sized for the chip; a CPU fallback at
            # SF-10 would blow any sane wall-clock budget. Rates are
            # per-row, so the smaller run stays comparable.
            sf = 1.0
            _log("cpu fallback: kernel tier auto-reduced to sf=1")

    from ydb_tpu.engine.blobs import DirBlobStore
    from ydb_tpu.engine.scan import ColumnSource, ScanExecutor
    from ydb_tpu.engine.shard import ColumnShard, ShardConfig
    from ydb_tpu.workload import tpch

    _log(f"generating TPC-H sf={sf:g}")
    data = tpch.TpchData(sf=sf, seed=42)
    li = data.tables["lineitem"]
    n_rows = len(li["l_orderkey"])
    src = ColumnSource(li, tpch.LINEITEM_SCHEMA, data.dicts)

    extra = {"sf": sf, "rows": n_rows, "engine_sf": engine_sf,
             "backend": jax.default_backend()}
    if tpu_unavailable:
        extra["tpu_unavailable"] = True

    # ---- CPU baseline: median of >= 5 runs + dispersion (VERDICT r3
    # weak #3, r5 weak #4): the median resists the one slow outlier a
    # noisy host throws in, and the coefficient of variation is
    # reported so a jittery baseline marks vs_baseline untrusted ----
    _log("CPU baselines")
    cutoff = tpch._days("1998-12-01") - 90
    d0, d1 = tpch._days("1994-01-01"), tpch._days("1995-01-01")
    n_base = max(5, iters)
    ts = []
    for _ in range(n_base):
        t0 = time.perf_counter()
        base1, _, nls = cpu_q1(li, cutoff)
        ts.append(time.perf_counter() - t0)
    cpu_q1_s = float(np.median(ts))
    cpu_q1_cv = float(np.std(ts) / np.mean(ts))
    extra["cpu_q1_rows_per_sec"] = round(n_rows / cpu_q1_s)
    extra["cpu_q1_runs"] = n_base
    extra["cpu_q1_cv"] = round(cpu_q1_cv, 3)
    ts = []
    for _ in range(n_base):
        t0 = time.perf_counter()
        base6 = cpu_q6(li, d0, d1)
        ts.append(time.perf_counter() - t0)
    cpu_q6_s = float(np.median(ts))
    extra["cpu_q6_rows_per_sec"] = round(n_rows / cpu_q6_s)
    extra["cpu_q6_cv"] = round(float(np.std(ts) / np.mean(ts)), 3)
    _checkpoint("cpu_baseline", extra)

    # ---- kernel tier: HBM-resident blocks -> compiled program ----
    _log("kernel tier: ingest + compile")
    ex1 = ScanExecutor(tpch.q1_program(), src, block_rows=block_rows)
    ex6 = ScanExecutor(tpch.q6_program(), src, block_rows=block_rows)
    read_cols = tuple(dict.fromkeys(ex1.read_cols + ex6.read_cols))
    t0 = time.perf_counter()
    blocks = [jax.device_put(b) for b in src.blocks(block_rows, read_cols)]
    jax.block_until_ready(blocks)
    hbm_ingest_s = time.perf_counter() - t0
    nbytes = sum(c.data.nbytes + c.validity.nbytes
                 for b in blocks for c in b.columns.values())
    extra["kernel_ingest_rows_per_sec"] = round(n_rows / hbm_ingest_s)
    extra["kernel_ingest_gb_per_sec"] = round(nbytes / hbm_ingest_s / 1e9, 3)

    def run_kernel(ex):
        def go():
            out = ex.finalize([ex.run_block(b) for b in blocks])
            jax.block_until_ready(out)
            return out
        return go

    cold1, warm1, out1 = timed_cold_warm(run_kernel(ex1), iters,
                                         budget - 90)
    cold6, warm6, out6 = timed_cold_warm(run_kernel(ex6), iters,
                                         budget - 90)
    check_q1(out1, li, nls, base1)
    rev = int(np.asarray(out6.to_numpy()["revenue"])[0])
    assert rev == base6, f"Q6 mismatch {rev} != {base6}"
    extra["kernel_q1_warm_rows_per_sec"] = round(n_rows / warm1)
    extra["kernel_q1_cold_rows_per_sec"] = round(n_rows / cold1)
    extra["kernel_q6_warm_rows_per_sec"] = round(n_rows / warm6)
    q1_bytes = sum(c.data.nbytes + c.validity.nbytes
                   for b in blocks for nm, c in b.columns.items()
                   if nm in ex1.read_cols)
    extra["kernel_hbm_gb_per_sec"] = round(q1_bytes / warm1 / 1e9, 1)
    _checkpoint("kernel", extra)

    skipped = extra.setdefault("skipped", [])

    # fused vs per-aggregate group-by A/B (PR 3 acceptance): on by
    # default for every backend; YDB_TPU_BENCH_FUSED_COMPARE=0 skips
    fflag = os.environ.get("YDB_TPU_BENCH_FUSED_COMPARE")
    fused_enabled = (fflag not in ("0", "", "off")) if fflag is not None \
        else True
    if fused_enabled and _budget_left(budget) > 120:
        _log("fused group-by A/B")
        extra.update(fused_ab(src, blocks, n_rows, block_rows,
                              max(2, iters // 2)))
        _checkpoint("fused_ab", extra)
    elif fused_enabled:
        skipped.append("fused_ab:budget")

    # Pallas one-hot group-by vs XLA scatter A/B (VERDICT r4 item 9):
    # by default on the real chip; force with YDB_TPU_BENCH_PALLAS_COMPARE
    flag = os.environ.get("YDB_TPU_BENCH_PALLAS_COMPARE")
    ab_enabled = (jax.default_backend() in ("tpu", "axon") if flag is None
                  else flag not in ("0", "", "off"))
    if ab_enabled and _budget_left(budget) > 120:
        _log("pallas A/B")
        extra.update(pallas_ab(src, blocks, n_rows, block_rows,
                               max(2, iters // 2)))
        _checkpoint("pallas_ab", extra)
    elif ab_enabled:
        skipped.append("pallas_ab:budget")
    del blocks

    # column-statistics tier: zone-map pruning A/B + NDV accuracy
    # (YDB_TPU_BENCH_STATS=0 skips; fail-soft like the storage tiers)
    if os.environ.get("YDB_TPU_BENCH_STATS", "1") not in ("0", "", "off"):
        if _budget_left(budget) > 90:
            _log("stats tier: pruning A/B + NDV")
            try:
                run_stats_ab(extra, iters)
            except Exception as e:  # noqa: BLE001 - additive evidence
                extra["stats_tier_error"] = repr(e)[-300:]
            _checkpoint("stats", extra)
        else:
            skipped.append("stats_tier:budget")

    # whole-plan fusion tier: fused single-dispatch vs per-fragment walk
    # (YDB_TPU_BENCH_FUSION=0 skips; fail-soft like the storage tiers)
    if os.environ.get("YDB_TPU_BENCH_FUSION", "1") not in ("0", "", "off"):
        if _budget_left(budget) > 90:
            _log("fusion tier: whole-plan A/B")
            try:
                run_fusion_ab(extra, iters)
            except Exception as e:  # noqa: BLE001 - additive evidence
                extra["fusion_tier_error"] = repr(e)[-300:]
            _checkpoint("fusion", extra)
        else:
            skipped.append("fusion_tier:budget")

    # mesh scale-out tier: sharded whole-plan dispatch vs single chip
    # (YDB_TPU_BENCH_MESH=0 skips; auto-skips under 2 devices)
    if os.environ.get("YDB_TPU_BENCH_MESH", "1") not in ("0", "", "off"):
        if _budget_left(budget) > 90:
            _log("mesh tier: sharded fused plans")
            try:
                run_mesh_tier(extra, max(2, iters // 2))
            except Exception as e:  # noqa: BLE001 - additive evidence
                extra["mesh_tier_error"] = repr(e)[-300:]
            _checkpoint("mesh", extra)
        else:
            skipped.append("mesh_tier:budget")

    # serving-throughput tier: concurrent sessions, batching on-vs-off
    # (YDB_TPU_BENCH_SERVING=0 skips; fail-soft like the storage tiers)
    if os.environ.get("YDB_TPU_BENCH_SERVING", "1") not in \
            ("0", "", "off"):
        if _budget_left(budget) > 150:
            _log("serving tier: concurrent-session QPS A/B")
            try:
                run_serving_tier(extra, budget)
            except Exception as e:  # noqa: BLE001 - additive evidence
                extra["serving_tier_error"] = repr(e)[-300:]
            _checkpoint("serving", extra)
        else:
            skipped.append("serving_tier:budget")

    # network serving tier: loopback pgwire clients, two weighted
    # tenants, batching on-vs-off (YDB_TPU_BENCH_NET=0 skips)
    if os.environ.get("YDB_TPU_BENCH_NET", "1") not in \
            ("0", "", "off"):
        if _budget_left(budget) > 150:
            _log("net tier: loopback pgwire multi-tenant QPS A/B")
            try:
                run_net_tier(extra, budget)
            except Exception as e:  # noqa: BLE001 - additive evidence
                extra["net_tier_error"] = repr(e)[-300:]
            _checkpoint("net", extra)
        else:
            skipped.append("net_tier:budget")

    engine_warm_rps = extra["kernel_q1_warm_rows_per_sec"]
    db_iters = min(iters, 2)  # storage tiers stream the table per run
    if not os.environ.get("YDB_TPU_BENCH_SKIP_ENGINE") \
            and _budget_left(budget) <= 60:
        skipped.append("engine_tier:budget")
    try:
      if not os.environ.get("YDB_TPU_BENCH_SKIP_ENGINE") \
              and _budget_left(budget) > 60:
        # ---- engine tier: ColumnShard on DirBlobStore ----
        # The storage tiers run at engine_sf (default SF-1): they
        # stream the whole table from disk per query run, so their
        # duration scales with data size while their rows/s rate does
        # not — SF-1 gives the same rate in a bounded wall-clock.
        if engine_sf == sf:
            eli, edicts = li, data.dicts
        else:
            _log(f"generating engine-tier data sf={engine_sf:g}")
            edata = tpch.TpchData(sf=engine_sf, seed=42)
            eli, edicts = edata.tables["lineitem"], edata.dicts
        e_rows = len(eli["l_orderkey"])
        extra["engine_rows"] = e_rows
        ebase1, _, enls = cpu_q1(eli, cutoff)
        ebase6 = cpu_q6(eli, d0, d1)
        with tempfile.TemporaryDirectory(prefix="ydbtpu_bench_") as root:
            store = DirBlobStore(root)
            shard = ColumnShard(
                "bench", tpch.LINEITEM_SCHEMA, store, dicts=edicts,
                config=ShardConfig(
                    compact_portion_threshold=10 ** 9,
                    scan_block_rows=block_rows,
                    portion_chunk_rows=1 << 18,
                ),
            )
            _log(f"engine tier: ingest {e_rows} rows")
            batch = 1 << 22
            t0 = time.perf_counter()
            for off in range(0, e_rows, batch):
                wid = shard.write(
                    {k: v[off:off + batch] for k, v in eli.items()})
                shard.commit([wid])
            ingest_s = time.perf_counter() - t0
            extra["engine_ingest_rows_per_sec"] = round(e_rows / ingest_s)
            stored = sum(
                len(store.get(f"bench/portion/{m.portion_id}"))
                for m in shard.visible_portions())
            extra["engine_stored_gb"] = round(stored / 1e9, 2)
            extra["engine_ingest_gb_per_sec"] = round(
                stored / ingest_s / 1e9, 3)

            def run_engine(prog):
                def go():
                    return shard.scan(prog)
                return go

            _log("engine tier: scans")
            from ydb_tpu.obs import profile as profile_mod
            from ydb_tpu.obs.counters import Histogram

            deadline = budget - 45
            ehist1 = Histogram()
            ecold1, ewarm1, eout1 = timed_cold_warm(
                run_engine(tpch.q1_program()), db_iters, deadline,
                hist=ehist1)
            # verify + record q1 BEFORE anything else can run out of
            # budget: measured numbers survive a mid-tier _BudgetSpent
            eres = {n: np.asarray(v[0]) for n, v in eout1.cols.items()}
            eng_gid = (eres["l_returnflag"].astype(np.int64) * enls
                       + eres["l_linestatus"].astype(np.int64))
            order = np.argsort(eng_gid)
            assert np.array_equal(eng_gid[order], ebase1["gid"])
            assert np.allclose(
                eres["sum_charge"].astype(np.float64)[order],
                ebase1["sum_charge"], rtol=1e-9)
            extra["engine_q1_cold_rows_per_sec"] = round(e_rows / ecold1)
            extra["engine_q1_warm_rows_per_sec"] = round(e_rows / ewarm1)
            # per-stage scan attribution of the LAST (warm) q1 run:
            # read (blob IO) / merge (K-way dedup) / stage (block build
            # + device transfer) / compute (device dispatch) seconds —
            # concurrent stages overlap, so they may sum past wall time
            extra["engine_q1_stage_seconds"] = dict(
                shard.last_scan_stages)
            lat = _latency_summary(ehist1)
            if lat:
                extra["engine_q1_latency"] = lat
            # one profiled warm run: the QueryProfile (stage seconds,
            # compile-vs-execute split, pruning) rides the bench JSON.
            # Budget-guarded like every other run — an extra scan past
            # the external kill threshold wedges the TPU claim.
            if _budget_left(budget) > 30:
                ph = _profiled_with_movement(
                    "q1", lambda: shard.scan(tpch.q1_program()),
                    extra, "engine_q1", "engine")
                extra["engine_q1_profile"] = ph.profile.to_dict()
                _syncsan_warm("q1",
                              lambda: shard.scan(tpch.q1_program()),
                              extra, "engine_q1_syncsan")
                _memsan_warm("q1",
                             lambda: shard.scan(tpch.q1_program()),
                             extra, "engine_q1_memsan")
            engine_warm_rps = round(e_rows / ewarm1)
            _checkpoint("engine_q1", extra)
            if _budget_left(budget) < 45:
                raise _BudgetSpent("engine_q6,sql_tier:budget")
            ehist6 = Histogram()
            ecold6, ewarm6, eout6 = timed_cold_warm(
                run_engine(tpch.q6_program()), db_iters, deadline,
                hist=ehist6)
            assert int(np.asarray(eout6.cols["revenue"][0])[0]) == ebase6
            extra["engine_q6_cold_rows_per_sec"] = round(e_rows / ecold6)
            extra["engine_q6_warm_rows_per_sec"] = round(e_rows / ewarm6)
            extra["engine_q6_stage_seconds"] = dict(
                shard.last_scan_stages)
            lat = _latency_summary(ehist6)
            if lat:
                extra["engine_q6_latency"] = lat
            if _budget_left(budget) > 30:
                ph = _profiled_with_movement(
                    "q6", lambda: shard.scan(tpch.q6_program()),
                    extra, "engine_q6", "engine")
                extra["engine_q6_profile"] = ph.profile.to_dict()
                _syncsan_warm("q6",
                              lambda: shard.scan(tpch.q6_program()),
                              extra, "engine_q6_syncsan")
                _memsan_warm("q6",
                             lambda: shard.scan(tpch.q6_program()),
                             extra, "engine_q6_memsan")
            _checkpoint("engine_q6", extra)

            # ---- resident tier: HBM-pinned columns vs the staged
            # engine path just measured (ROADMAP item 1: close the
            # engine-vs-kernel gap by not re-ingesting per scan).
            # Heat-driven promotion (two host scans cross the
            # threshold), drained before timing so warm scans assemble
            # blocks from device-resident arrays.
            if os.environ.get("YDB_TPU_BENCH_RESIDENT", "1") != "0" \
                    and _budget_left(budget) > 60:
                from ydb_tpu.engine import resident as resident_mod

                _log("resident tier: promote + warm scans")
                try:
                    resident_mod.RESIDENT_FORCE = True
                    for prog in (tpch.q1_program(), tpch.q6_program()):
                        shard.scan(prog)
                        shard.scan(prog)
                    shard.resident.drain()
                    _rc1, rwarm1, rout1 = timed_cold_warm(
                        run_engine(tpch.q1_program()), db_iters,
                        deadline)
                    # bit-identity vs the CPU baseline (the same check
                    # the staged path passed above)
                    rres = {n: np.asarray(v[0])
                            for n, v in rout1.cols.items()}
                    rgid = (rres["l_returnflag"].astype(np.int64) * enls
                            + rres["l_linestatus"].astype(np.int64))
                    rorder = np.argsort(rgid)
                    assert np.array_equal(rgid[rorder], ebase1["gid"])
                    assert np.allclose(
                        rres["sum_charge"].astype(np.float64)[rorder],
                        ebase1["sum_charge"], rtol=1e-9)
                    extra["engine_q1_resident_rows_per_sec"] = round(
                        e_rows / rwarm1)
                    extra["resident_q1_speedup"] = round(
                        ewarm1 / rwarm1, 2)
                    extra["engine_q1_resident_stage_seconds"] = dict(
                        shard.last_scan_stages)
                    _rc6, rwarm6, rout6 = timed_cold_warm(
                        run_engine(tpch.q6_program()), db_iters,
                        deadline)
                    assert int(np.asarray(
                        rout6.cols["revenue"][0])[0]) == ebase6
                    extra["engine_q6_resident_rows_per_sec"] = round(
                        e_rows / rwarm6)
                    extra["resident_q6_speedup"] = round(
                        ewarm6 / rwarm6, 2)
                    extra["resident_store"] = shard.resident.snapshot()
                    # ROADMAP item 1 scoreboard: warm engine Q1 as a
                    # fraction of the kernel-tier roofline (was ~200x
                    # away; the resident tier should land single-digit)
                    k1 = extra.get("kernel_q1_warm_rows_per_sec")
                    if k1:
                        extra["resident_roofline_gap_q1"] = round(
                            k1 / max(round(e_rows / rwarm1), 1), 2)
                    _log(f"resident tier: q1 x"
                         f"{extra['resident_q1_speedup']} q6 x"
                         f"{extra['resident_q6_speedup']} roofline gap "
                         f"{extra.get('resident_roofline_gap_q1')}")
                finally:
                    resident_mod.RESIDENT_FORCE = None
                    shard.resident.clear()
                _checkpoint("engine_resident", extra)

            # ---- sql tier: parse -> plan -> execute over the store ----
            if _budget_left(budget) < 60:
                raise _BudgetSpent("sql_tier:budget")
            from ydb_tpu.engine.reader import MultiShardStreamSource
            from ydb_tpu.plan import Database, execute_plan, to_host
            from ydb_tpu.sql.parser import parse
            from ydb_tpu.sql.planner import Catalog, plan_select_full
            from ydb_tpu.workload.queries import TPCH

            # probe the SQL path at a tiny scale first: it has the same
            # compile + per-block dispatch structure as the full run,
            # so a pathologically slow backend (e.g. a high-latency
            # device tunnel) is detected in seconds, not tens of
            # minutes — the tier is then skipped with an explicit
            # marker instead of eating the whole budget
            _log("sql tier: probe")
            pdata = tpch.TpchData(sf=0.02, seed=43)
            pshard = ColumnShard(
                "probe", tpch.LINEITEM_SCHEMA, store,
                dicts=pdata.dicts,
                config=ShardConfig(
                    compact_portion_threshold=10 ** 9,
                    scan_block_rows=block_rows,
                    portion_chunk_rows=1 << 18))
            pshard.commit([pshard.write(
                dict(pdata.tables["lineitem"]))])
            pcat = Catalog(schemas={"lineitem": tpch.LINEITEM_SCHEMA},
                           primary_keys={}, dicts=pdata.dicts)
            pdb = Database(
                sources={"lineitem": MultiShardStreamSource(
                    [pshard], tpch.LINEITEM_SCHEMA, pdata.dicts)},
                dicts=pdata.dicts)
            pplan = plan_select_full(parse(TPCH["q1"]), pcat).plan
            t0 = time.perf_counter()
            to_host(execute_plan(pplan, pdb))
            probe_s = time.perf_counter() - t0
            extra["sql_probe_cold_s"] = round(probe_s, 1)
            probe_cap = min(300.0, _budget_left(budget) / 4)
            if probe_s > probe_cap:
                raise _SqlProbeTooSlow(
                    f"sql probe took {probe_s:.0f}s (cap "
                    f"{probe_cap:.0f}s)")

            _log("sql tier")
            catalog = Catalog(
                schemas={"lineitem": tpch.LINEITEM_SCHEMA},
                primary_keys={}, dicts=edicts)
            # ONE Database so the compiled-program cache persists across
            # runs: warm measures steady state (storage IO + execution),
            # not retracing. The stream source restarts per blocks() call.
            sql_db = Database(
                sources={"lineitem": MultiShardStreamSource(
                    [shard], tpch.LINEITEM_SCHEMA, edicts)},
                dicts=edicts)
            # node-scoped HBM block cache, as a Cluster would attach
            # (warm SQL runs measure device compute, not re-decode)
            from ydb_tpu.engine.blockcache import DeviceBlockCache

            sql_db.block_cache = DeviceBlockCache()

            def run_sql(sql):
                plan = plan_select_full(parse(sql), catalog).plan

                def go():
                    return to_host(execute_plan(plan, sql_db))
                return go

            shist1 = Histogram()
            scold1, swarm1, sout1 = timed_cold_warm(
                run_sql(TPCH["q1"]), db_iters, deadline, hist=shist1)
            assert np.allclose(
                np.sort(np.asarray(sout1.cols["count_order"][0])),
                np.sort(ebase1["count"]))
            extra["sql_q1_cold_rows_per_sec"] = round(e_rows / scold1)
            extra["sql_q1_warm_rows_per_sec"] = round(e_rows / swarm1)
            lat = _latency_summary(shist1)
            if lat:
                extra["sql_q1_latency"] = lat
            if _budget_left(budget) > 30:
                ph = _profiled_with_movement(
                    TPCH["q1"], run_sql(TPCH["q1"]),
                    extra, "sql_q1", "sql")
                extra["sql_q1_profile"] = ph.profile.to_dict()
            if _budget_left(budget) < 45:
                raise _BudgetSpent("sql_q6:budget")
            scold6, swarm6, sout6 = timed_cold_warm(
                run_sql(TPCH["q6"]), db_iters, deadline)
            assert int(np.asarray(sout6.cols["revenue"][0])[0]) == ebase6
            extra["sql_q6_warm_rows_per_sec"] = round(e_rows / swarm6)
            _checkpoint("sql", extra)
    except _SqlProbeTooSlow as e:
        # the engine tier SUCCEEDED; only the SQL tier is skipped
        skipped.append(f"sql_tier:{e}")
    except _BudgetSpent as e:
        # everything measured so far stays; what remains is skipped
        skipped.append(str(e))
    except Exception as e:  # noqa: BLE001 - storage tiers fail soft:
        # the kernel-tier numbers (already verified) still report
        extra["engine_tier_error"] = repr(e)[-400:]
    try:
        run_ooc(extra, iters, block_rows)
        if "ooc" in extra:
            _checkpoint("ooc", extra)
    except Exception as e:  # noqa: BLE001 - OOC is additive evidence
        extra.setdefault("ooc", {})["error"] = repr(e)[-400:]
    _log("done")

    extra["baseline"] = ("vectorized numpy single-pass (mask+bincount), "
                         f"same host, median of {n_base} runs; rates "
                         "are per-row so cross-SF comparable")
    # label the metric with the SF it was actually measured at: the
    # engine tier runs at engine_sf; if it failed/was skipped the value
    # falls back to the kernel tier at sf
    metric_sf = engine_sf if "engine_q1_warm_rows_per_sec" in extra \
        else sf
    report = {
        "metric": f"tpch_q1_sf{metric_sf:g}_engine_rows_per_sec",
        "value": engine_warm_rps,
        "unit": "rows/s",
        "vs_baseline": round(engine_warm_rps / (n_rows / cpu_q1_s), 3),
        "extra": extra,
    }
    if cpu_q1_cv > 0.3:
        # the CPU baseline scattered too much for its median to anchor
        # a ratio (shared/noisy host): the absolute rows/s numbers
        # stand, the comparison does not (VERDICT r5 weak #4)
        report["vs_baseline_untrusted"] = True
        report["vs_baseline_untrusted_reason"] = (
            f"cpu baseline cv={cpu_q1_cv:.3f} > 0.3 over "
            f"{n_base} runs")
    _checkpoint("final", extra)
    print(json.dumps(report))


if __name__ == "__main__":
    main()
