"""Benchmark: TPC-H Q1 scan+filter+group-by throughput on the device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config (BASELINE.md config 1/2): TPC-H Q1 at SF (default 1.0 — ~6M
lineitem rows), executed by the block-streamed columnar engine on the
default JAX device (the real TPU chip under the driver). The baseline is
the single-threaded CPU reference engine (ydb_tpu.engine.oracle) on the
identical data — the stand-in for the reference's single-node CPU KQP
baseline, which BASELINE.md notes must be measured, not copied (the
reference publishes no numbers and its 2M-LoC C++ server cannot be built
in this image).

Env knobs: YDB_TPU_BENCH_SF (default 1.0), YDB_TPU_BENCH_ITERS (default 5),
YDB_TPU_BENCH_BLOCK_ROWS (default 2^21).
"""

import json
import os
import time

import numpy as np


def main():
    sf = float(os.environ.get("YDB_TPU_BENCH_SF", "1.0"))
    iters = int(os.environ.get("YDB_TPU_BENCH_ITERS", "5"))
    block_rows = int(os.environ.get("YDB_TPU_BENCH_BLOCK_ROWS", str(1 << 21)))

    import jax

    from ydb_tpu.engine.oracle import OracleTable, run_oracle
    from ydb_tpu.engine.scan import ColumnSource, ScanExecutor
    from ydb_tpu.workload import tpch

    data = tpch.TpchData(sf=sf, seed=42)
    li = data.tables["lineitem"]
    n_rows = len(li["l_orderkey"])
    src = ColumnSource(
        columns=li, schema=tpch.LINEITEM_SCHEMA, dicts=data.dicts
    )
    prog = tpch.q1_program()

    ex = ScanExecutor(prog, src, block_rows=block_rows)
    # preload device-resident blocks (the engine's steady state: data lives
    # in HBM portions; host->HBM transfer is the ingest path, not the scan)
    blocks = [
        jax.device_put(b) for b in src.blocks(block_rows, ex.read_cols)
    ]
    jax.block_until_ready(blocks)

    def run_once():
        partials = [ex.run_block(b) for b in blocks]
        out = ex.finalize(partials)
        jax.block_until_ready(out.length)
        return out

    run_once()  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run_once()
    dt = (time.perf_counter() - t0) / iters
    device_rps = n_rows / dt

    # CPU baseline (single-thread numpy reference engine, same data)
    oracle_tbl = OracleTable(
        {n: (v, np.ones(len(v), dtype=bool)) for n, v in li.items()},
        tpch.LINEITEM_SCHEMA,
    )
    t0 = time.perf_counter()
    ora = run_oracle(prog, oracle_tbl, data.dicts)
    cpu_dt = time.perf_counter() - t0
    cpu_rps = n_rows / cpu_dt

    # sanity: engine result matches oracle
    res = ex.finalize([ex.run_block(b) for b in blocks])
    res_host = np.asarray(res.columns["count_order"].data)[: int(res.length)]
    ora_host = ora.cols["count_order"][0]
    assert sorted(res_host.tolist()) == sorted(ora_host.tolist()), (
        "engine/oracle mismatch"
    )

    print(json.dumps({
        "metric": f"tpch_q1_sf{sf:g}_scan_rows_per_sec",
        "value": round(device_rps),
        "unit": "rows/s",
        "vs_baseline": round(device_rps / cpu_rps, 3),
    }))


if __name__ == "__main__":
    main()
