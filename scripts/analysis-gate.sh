#!/usr/bin/env bash
# Pre-commit / CI analysis gate: run every static-analysis pillar
# (verify self-test, lint, concurrency, lifecycle, hotpath, devmem)
# over the files git reports changed, exiting with the analyzer's
# status.
#
#   scripts/analysis-gate.sh                    # changed .py files only
#   scripts/analysis-gate.sh --full             # the whole tree
#   scripts/analysis-gate.sh ydb_tpu/serving …  # explicit paths/dirs
#
# Prints per-stage finding counts; on failure the findings themselves
# (file:line:col: CODE [name] message) so the breakage is actionable
# without re-running anything. Documented in ydb_tpu/analysis/README.md.
set -euo pipefail

cd "$(dirname "$0")/.."

SCOPE=(--changed)
if [[ "${1:-}" == "--full" ]]; then
    SCOPE=()
elif [[ $# -gt 0 ]]; then
    SCOPE=("$@")  # gate a subsystem: scripts/analysis-gate.sh ydb_tpu/serving
fi

out=$(JAX_PLATFORMS=cpu python -m ydb_tpu.analysis "${SCOPE[@]}" --json) \
    && rc=0 || rc=$?

python - "$rc" <<'PY' "$out"
import json
import sys

rc = int(sys.argv[1])
stages = json.loads(sys.argv[2])
total = 0
for stage, findings in stages.items():
    print(f"{stage}: {len(findings)} finding(s)")
    total += len(findings)
    for f in findings:
        print(f"  {f['file']}:{f['line']}:{f['col']}: "
              f"{f['code']} [{f['name']}] {f['message']}")
if total == 0 and rc == 0:
    print("analysis gate: clean")
else:
    print(f"analysis gate: {total} finding(s) — fix, mark "
          "@analysis.host_ok(reason), or suppress with a justified "
          "'# ydb-lint: disable=<code>' pragma")
sys.exit(rc)
PY
